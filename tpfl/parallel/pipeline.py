"""Pipeline parallelism — GPipe-style microbatch schedule on a ``pp``
mesh axis, trainable end-to-end.

Completes the parallelism inventory next to dp/FSDP (ShardedTrainer),
sequence parallelism (ring_attention) and the federated node axis
(VmapFederation). The reference has no intra-model parallelism at all
(SURVEY §2.10).

Design (TPU-idiomatic, no per-stage Python processes): the model is a
stack of L identical blocks; each of the n pipeline stages owns L/n
consecutive blocks (their params live only on that stage's device —
total param memory is split n ways). Inside ``shard_map`` every stage
runs the same SPMD program: at each of ``n_micro + n - 1`` ticks it
applies its blocks to the activation it holds, then ``ppermute``\\ s the
result to the next stage over ICI. Stage 0 feeds a fresh microbatch
each tick; the last stage emits finished microbatches. Bubble fraction
is the usual (n-1)/(n_micro + n - 1).

Training: the tick loop is a ``lax.scan`` (not ``fori_loop``), so
reverse-mode AD works — JAX's scan transpose replays the ticks in
reverse with stashed activations (the GPipe backward schedule), and the
``ppermute`` transposes to the reverse ring, carrying activation
cotangents stage i+1 -> i over ICI. ``make_pipeline_trainer`` wraps
this in a jitted loss/grad/optimizer step whose gradients are exactly
the sequential model's.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax

from tpfl.parallel.compat import shard_map
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _stage_apply(block_fn: Callable, stage_params, x):
    """Apply this stage's chunk of blocks: scan over the local layer
    axis (params stacked [layers_per_stage, ...])."""

    def body(h, layer_params):
        # Pin the carry dtype: a promoting block_fn (bf16 activations ×
        # f32 params) must not break the scan carry-type invariant.
        return block_fn(layer_params, h).astype(x.dtype), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def pipeline_forward(
    block_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    axis_name: str = "pp",
) -> jnp.ndarray:
    """Run inside shard_map. ``stage_params``: this stage's stacked
    block params [L/n, ...]; ``microbatches``: [n_micro, mb, ...] —
    replicated input (every stage sees it; only stage 0 consumes).
    Returns [n_micro, mb, ...] finished activations (valid on the LAST
    stage; other stages return garbage of the same shape).

    Differentiable: ticks are a ``lax.scan`` and the output bank is
    updated with index arithmetic + ``where`` (no data-dependent
    control flow), so ``jax.grad`` through this runs the backward
    pipeline schedule."""
    n = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    perm = [(i, i + 1) for i in range(n - 1)]  # forward shifts only

    def tick(carry, t):
        held, outputs = carry
        # Stage 0 picks up microbatch t (if any left); others keep what
        # the previous stage sent them.
        feed = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, n_micro - 1), keepdims=False
        )
        x = jnp.where(stage == 0, feed, held)
        y = _stage_apply(block_fn, stage_params, x)
        # Last stage banks microbatch t - (n - 1) once it's real: write
        # y at the clamped slot, but keep the slot's previous value
        # while the pipe is still filling (out_idx < 0).
        out_idx = t - (n - 1)
        idx = jnp.clip(out_idx, 0, n_micro - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, idx, keepdims=False)
        slot = jnp.where(out_idx >= 0, y, prev)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, slot, idx, axis=0
        )
        # Hand activations down the pipe (stage i -> i+1).
        held = jax.lax.ppermute(y, axis_name, perm)
        return (held, outputs), None

    held = jnp.zeros(mb_shape, microbatches.dtype)
    outputs = jnp.zeros((n_micro, *mb_shape), microbatches.dtype)
    (held, outputs), _ = jax.lax.scan(
        tick, (held, outputs), jnp.arange(n_micro + n - 1)
    )
    # Leading per-stage axis: only the LAST stage's outputs are real;
    # the caller slices them out of the stage-sharded global result.
    return outputs[None]


def _shard_stage_params(mesh: Mesh, spec: PartitionSpec, params: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda p: jax.device_put(p, NamedSharding(mesh, spec)), params
    )


def make_pipeline(
    mesh: Mesh,
    block_fn: Callable,
    n_layers: int,
    axis_name: str = "pp",
):
    """Build a jitted pipelined forward over ``mesh[axis_name]``.

    ``block_fn(layer_params, x) -> x`` applies ONE block. Global params
    arrive stacked [n_layers, ...] and are sharded so each stage holds
    its own [n_layers/n, ...] slice (param memory splits across
    stages). Microbatches are replicated in; outputs are read from the
    last stage."""
    n = mesh.shape[axis_name]
    if n_layers % n:
        raise ValueError(f"{n_layers} layers do not split over {n} stages")
    param_spec = PartitionSpec(axis_name)

    fn = shard_map(
        partial(pipeline_forward, block_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_spec, PartitionSpec()),
        out_specs=PartitionSpec(axis_name),  # per-stage leading axis
        check_vma=False,
    )

    def apply(stacked_params: Any, microbatches: jnp.ndarray) -> jnp.ndarray:
        stacked_params = _shard_stage_params(mesh, param_spec, stacked_params)
        return fn(stacked_params, microbatches)[-1]  # last stage's bank

    return jax.jit(apply)


def make_pipeline_trainer(
    mesh: Mesh,
    block_fn: Callable,
    n_layers: int,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 0.01,
    axis_name: str = "pp",
):
    """Trainable pipeline: returns ``(init, step)``.

    ``loss_fn(outputs, targets) -> scalar`` consumes the last stage's
    microbatch bank [n_micro, mb, ...]. ``init(stacked_params)`` shards
    the [n_layers, ...] param stack over the stages and builds optimizer
    state (sharded the same way — each stage updates only its layers).
    ``step(params, opt_state, microbatches, targets) -> (params,
    opt_state, loss)`` is one jitted fwd+bwd+update: the scan transpose
    replays the ticks backward (stashed activations, reverse-ring
    ppermute of cotangents), and gradients equal the sequential
    model's — tested in
    ``tests/test_parallel.py::test_pipeline_training_matches_sequential``.
    """
    n = mesh.shape[axis_name]
    if n_layers % n:
        raise ValueError(f"{n_layers} layers do not split over {n} stages")
    param_spec = PartitionSpec(axis_name)
    opt = optimizer or optax.sgd(learning_rate)

    fwd = shard_map(
        partial(pipeline_forward, block_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_spec, PartitionSpec()),
        out_specs=PartitionSpec(axis_name),
        check_vma=False,
    )

    def loss_of(params, microbatches, targets):
        outputs = fwd(params, microbatches)[-1]
        return loss_fn(outputs, targets)

    def step(params, opt_state, microbatches, targets):
        loss, grads = jax.value_and_grad(loss_of)(
            params, microbatches, targets
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))

    def init(stacked_params: Any):
        stacked_params = _shard_stage_params(mesh, param_spec, stacked_params)
        return stacked_params, opt.init(stacked_params)

    return init, jstep
