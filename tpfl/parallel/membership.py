"""MembershipView — elastic membership over the fused engine's padded
node axis (zero-recompile churn).

The engine compiles its round programs at a padded CAPACITY TIER
(:func:`~tpfl.parallel.mesh.capacity_tier` — pow-2 buckets, further
padded to a device multiple like any node count), not at the live
member count. This view owns the mapping from live peer addresses to
padded slots, so every membership event the ops plane sees —

- **join**: a fresh peer takes the lowest free slot (stable slot
  reuse keeps a rejoining peer's row where its state already is);
- **leave / crash**: the slot returns to the free list and its fold
  weight drops to zero — the row's stale params ride along untouched
  (their weight is zero, exactly like the mesh pad rows);
- **quarantine / readmit**: the verdict flips the slot's weight, the
  slot itself is KEPT — eviction is a mask edit, never a restack;

— becomes a pure edit of the ``[capacity]`` weight vector
(:meth:`weights`). The program's cache key, abstract shapes and
compiled bytes are all functions of the tier, so churn inside a tier
runs **zero recompiles** (the CompileObservatory's
``signature_counts`` is the receipt; the bench ``elastic`` tier gates
it). Only crossing a tier boundary (:meth:`maybe_resize`) re-lowers —
and demoting back to a previously-visited tier re-uses its cached
program, so even tier oscillation compiles each tier once.

Concurrency: churn events arrive from protocol threads (gossip,
fault injection) while the fit thread reads the mask between windows
— all mutable state sits under one ``make_lock`` leaf lock, matching
the quarantine engine's discipline.
"""

from __future__ import annotations

import heapq
from typing import Any, Optional

import numpy as np

from tpfl.concurrency import make_lock
from tpfl.parallel.mesh import capacity_tier
from tpfl.settings import Settings

#: Demotion hysteresis: a tier is shed only once the live count falls
#: to a QUARTER of capacity (i.e. the demoted tier would still be at
#: most half full) — join/leave flapping around a boundary must not
#: oscillate compiles.
_DEMOTE_FILL = 0.25

#: Retained tier-change records (the promotions-only receipt).
_TIER_LOG_CAP = 1024


class MembershipView:
    """Live peer addrs → stable padded slots at a pow-2 capacity tier.

    Args:
        addrs: initial members (joined in order, slots 0..n-1).
        capacity_min: tier floor; defaults to
            ``Settings.ELASTIC_CAPACITY_MIN``.
        node: owner tag for telemetry/debug.
    """

    def __init__(
        self,
        addrs: "tuple[str, ...] | list[str]" = (),
        capacity_min: Optional[int] = None,
        node: str = "membership",
    ) -> None:
        self.node = node
        self._cap_min = int(
            Settings.ELASTIC_CAPACITY_MIN
            if capacity_min is None
            else capacity_min
        )
        self._lock = make_lock("MembershipView._lock")
        # addr -> padded slot index (< capacity).
        # guarded-by: _lock
        self._slots: dict[str, int] = {}
        # Freed slot heap — lowest-slot reuse keeps the live rows dense
        # at the front of the padded axis.
        # guarded-by: _lock
        self._free: list[int] = []
        # Slotted but weight-masked to zero (verdicts flow into the
        # mask, never restack state).
        # guarded-by: _lock
        self._quarantined: set[str] = set()
        # Bounded tier-change log ({"kind","capacity","live"}) — the
        # bench gates recompile count == promotion count.
        # guarded-by: _lock
        self._tier_log: list[dict] = []
        # guarded-by: _lock — next never-used slot ordinal.
        self._next = 0
        self.capacity = capacity_tier(len(addrs), self._cap_min)
        for a in addrs:
            self.join(a)

    # --- churn events ----------------------------------------------------

    def join(self, addr: str) -> int:
        """Admit ``addr``; returns its slot. Idempotent for a live
        member. When every slot is taken the tier PROMOTES (capacity
        doubles) — the one churn event that costs a compile."""
        with self._lock:
            slot = self._slots.get(addr)
            if slot is not None:
                return slot
            if self._free:
                slot = heapq.heappop(self._free)
            else:
                slot = self._next
                self._next += 1
                if slot >= self.capacity:
                    self.capacity = capacity_tier(slot + 1, self._cap_min)
                    self._log_tier("promote")
            self._slots[addr] = slot
            return slot

    def leave(self, addr: str) -> Optional[int]:
        """Graceful departure: the slot returns to the free list (its
        stale row rides at zero weight). Returns the freed slot, or
        None for an unknown addr."""
        with self._lock:
            slot = self._slots.pop(addr, None)
            if slot is not None:
                heapq.heappush(self._free, slot)
            self._quarantined.discard(addr)
            return slot

    def crash(self, addr: str) -> Optional[int]:
        """Crash eviction — identical mask edit to :meth:`leave` (the
        fault injector's path; the distinction is for the caller's
        bookkeeping, not the mask's)."""
        return self.leave(addr)

    def quarantine(self, addr: str) -> bool:
        """Zero ``addr``'s fold weight, KEEPING its slot — readmission
        is another mask edit away. False for a non-member."""
        with self._lock:
            if addr not in self._slots:
                return False
            self._quarantined.add(addr)
            return True

    def readmit(self, addr: str) -> bool:
        with self._lock:
            if addr not in self._quarantined:
                return False
            self._quarantined.discard(addr)
            return True

    def apply_verdicts(self, quarantined: "set[str]") -> None:
        """Reconcile with a :class:`~tpfl.management.quarantine
        .QuarantineEngine`'s active set (``quarantined()``): members in
        the set are masked, members no longer in it are readmitted —
        the verdict→mask seam the learner calls between windows."""
        with self._lock:
            self._quarantined = {a for a in quarantined if a in self._slots}

    # --- the mask --------------------------------------------------------

    def weights(
        self, base: "Optional[dict[str, float]]" = None
    ) -> np.ndarray:
        """The ``[capacity]`` f32 fold-weight vector: ``base``'s weight
        (default 1.0) at each live, non-quarantined member's slot, 0.0
        everywhere else — free slots, departed peers and quarantined
        members all read as mesh padding to the compiled program."""
        with self._lock:
            w = np.zeros((self.capacity,), np.float32)
            for addr, slot in self._slots.items():
                if addr in self._quarantined:
                    continue
                w[slot] = 1.0 if base is None else float(base.get(addr, 1.0))
        return w

    def mask(self) -> np.ndarray:
        """Alias of :meth:`weights` with unit weights."""
        return self.weights()

    # --- queries ---------------------------------------------------------

    def slot_of(self, addr: str) -> Optional[int]:
        with self._lock:
            return self._slots.get(addr)

    def members(self) -> "dict[str, int]":
        """addr -> slot snapshot (live members, quarantined included)."""
        with self._lock:
            return dict(self._slots)

    def quarantined(self) -> "set[str]":
        with self._lock:
            return set(self._quarantined)

    @property
    def live(self) -> int:
        """Live member count (quarantined members still hold slots)."""
        with self._lock:
            return len(self._slots)

    def tier_events(self) -> "list[dict]":
        with self._lock:
            return [dict(e) for e in self._tier_log]

    def promotions(self) -> int:
        """Tier promotions so far — the bench's allowed-recompile
        budget (recompile count == promotions, nothing else)."""
        with self._lock:
            return sum(1 for e in self._tier_log if e["kind"] == "promote")

    # --- tier control ----------------------------------------------------

    def maybe_resize(self, controller: Optional[Any] = None) -> Optional[int]:
        """Demote the capacity tier when the fleet has durably shrunk
        (live ≤ capacity × 0.25 — the demoted tier stays ≤ half full,
        so boundary flapping can't oscillate compiles). When an
        :class:`~tpfl.learning.async_control.AsyncController` is
        handed in, demotion DEFERS under staleness pressure: a fleet
        whose trainers already lag the version frontier should not eat
        a re-lowering stall on top. Returns the new capacity, or None
        when the tier holds. (Promotion happens eagerly in
        :meth:`join` — a member with no slot cannot wait.)"""
        tau = None
        if controller is not None:
            try:
                tau = controller.state_export().get("tau_mean")
            except Exception:
                tau = None
        with self._lock:
            used = len(self._slots)
            target = capacity_tier(used, self._cap_min)
            if target >= self.capacity:
                return None
            if used > self.capacity * _DEMOTE_FILL:
                return None
            if tau is not None and float(tau) > 2.0:
                return None  # staleness pressure: hold the tier
            # Compact: reassign live members (sorted by old slot) into
            # 0..n-1 so every slot fits the demoted tier.
            order = sorted(self._slots.items(), key=lambda kv: kv[1])
            self._slots = {addr: i for i, (addr, _) in enumerate(order)}
            self._free = []
            self._next = len(self._slots)
            self.capacity = target
            self._log_tier("demote")
            return target

    def _log_tier(self, kind: str) -> None:
        """Caller holds ``self._lock``."""
        self._tier_log.append(
            {"kind": kind, "capacity": int(self.capacity),
             "live": len(self._slots)}
        )
        if len(self._tier_log) > _TIER_LOG_CAP:
            del self._tier_log[: len(self._tier_log) - _TIER_LOG_CAP]

    # --- checkpoint ------------------------------------------------------

    def state_export(self) -> dict:
        """Checkpointable snapshot (host scalars/dicts only) — rides
        the engine checkpoint so a resumed host rebuilds the same
        addr→slot map (slot stability survives preemption)."""
        with self._lock:
            return {
                "capacity": int(self.capacity),
                "cap_min": int(self._cap_min),
                "slots": dict(self._slots),
                "free": sorted(self._free),
                "quarantined": sorted(self._quarantined),
                "next": int(self._next),
                "tier_log": [dict(e) for e in self._tier_log],
            }

    def state_import(self, state: dict) -> None:
        """Restore a :meth:`state_export` snapshot in place."""
        with self._lock:
            self.capacity = int(state["capacity"])
            self._cap_min = int(state.get("cap_min", self._cap_min))
            self._slots = {str(k): int(v) for k, v in state["slots"].items()}
            self._free = list(int(s) for s in state.get("free", []))
            heapq.heapify(self._free)
            self._quarantined = set(state.get("quarantined", []))
            self._next = int(state.get("next", len(self._slots)))
            self._tier_log = [dict(e) for e in state.get("tier_log", [])]

    @classmethod
    def from_state(cls, state: dict, node: str = "membership") -> "MembershipView":
        view = cls(capacity_min=int(state.get("cap_min", 1)), node=node)
        view.state_import(state)
        return view
