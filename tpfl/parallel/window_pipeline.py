"""WindowPipeline — the free-running engine driver (Sebulba split).

Podracer's Sebulba architecture (PAPERS.md) pins host work and device
compute to SEPARATE streams and double-buffers between them. The fused
engine already compiles K federation rounds into one device dispatch
(:class:`~tpfl.parallel.engine.FederationEngine`), but a sequential
driver still pays, BETWEEN windows, the host-side costs the device
never needed to wait for: the telemetry fan-out
(``engine_obs.replay_window``), profiler bookkeeping, next-window data
staging, and the dispatch RTT itself.

This driver exploits what JAX gives for free — async dispatch (a
program call returns output FUTURES while the device works) and buffer
donation (window N+1 consumes window N's output buffers in place) — to
run the engine free:

::

    device |  win N  ||  win N+1  ||  win N+2  | ...
    host   | dispatch N+1 ; finalize N (telemetry replay, profiler)
           | stage N+2's data on the prefetch thread ; dispatch N+2 ...

Steady state: the device's dispatch queue is never empty, so dispatch
RTT and host work vanish from wall clock; the measured inter-window
device-idle gap (:attr:`WindowPipeline.idle_gaps`, fed from the
``jax.Array.is_ready`` probe before each dispatch) collapses to the
argument-prep sliver — the ``engine_async`` bench tier gates the ≥2x
cut vs sequential dispatch.

Determinism: the pipeline reorders HOST work only — the device sees
the identical program sequence over the identical buffers, so
same-seed runs stay byte-identical to chained
``FederationEngine.run_rounds`` calls (tests/test_engine_async.py
proves it at 1 and 8 devices, donation report still clean).

Double-buffer ownership: with donation on, window N's input state is
consumed by the device program; the ONLY live copy of the federation
state is window N's output futures, which this driver chains straight
into window N+1's dispatch. At most two windows are ever in flight, so
at most two state buffers exist — the explicit double buffer.

Concurrency: the prefetch thread (:class:`WindowPrefetcher`) is a
named, single-slot stager guarded by ``tpfl.concurrency.make_lock``
(deadlock-ordering tracked under ``LOCK_TRACING``); it is joined at
every take and on shutdown — no thread outlives :meth:`run`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from tpfl import concurrency
from tpfl.management.telemetry import metrics
from tpfl.parallel.engine import EngineWindow, FederationEngine, FedBuffSchedule
from tpfl.settings import Settings

# data_for(window_index, start_round, n_rounds) -> (xs, ys) or None
# (None = reuse the current window's arrays).
DataSupplier = Callable[[int, int, int], "Optional[tuple[Any, Any]]"]


class WindowPrefetcher:
    """Single-slot background stager for the next window's data.

    One named thread per window: :meth:`start` launches it to run the
    supplier (shuffle + ``device_put`` placement — pure host/transfer
    work), :meth:`take` joins it and hands the staged arrays over. The
    slot is guarded by a :func:`tpfl.concurrency.make_lock` lock, and
    a thread is ALWAYS joined before the next starts and on
    :meth:`close` — the pipeline leaks no threads past its run.
    """

    def __init__(
        self, fn: DataSupplier, name: str = "tpfl-window-prefetch"
    ) -> None:
        self._fn = fn
        self._name = name
        self._lock = concurrency.make_lock("WindowPrefetcher._lock")
        self._thread: Optional[threading.Thread] = None
        # guarded-by: _lock — (window_index, staged_data, error)
        self._slot: Optional[tuple] = None

    def start(self, widx: int, start_round: int, n_rounds: int) -> None:
        """Stage window ``widx``'s data in the background (joins any
        previous stage first — one in flight)."""
        self.close()

        def work() -> None:
            out, err = None, None
            try:
                out = self._fn(widx, start_round, n_rounds)
            except BaseException as e:  # surfaced at take()
                err = e
            with self._lock:
                self._slot = (widx, out, err)

        self._thread = threading.Thread(
            target=work, name=f"{self._name}[{widx}]", daemon=True
        )
        self._thread.start()

    def take(self, widx: int) -> "Optional[tuple[Any, Any]]":
        """Join the stage and return window ``widx``'s staged data
        (None when nothing was staged for it); re-raises a supplier
        error on the caller's thread."""
        self.close()
        with self._lock:
            slot, self._slot = self._slot, None
        if slot is None:
            return None
        staged_widx, out, err = slot
        if err is not None:
            raise err
        return out if staged_widx == widx else None

    def close(self) -> None:
        """Join any in-flight stage (idempotent)."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()


def _outputs_ready(window: EngineWindow) -> bool:
    """True when the window's device work has provably completed (the
    non-blocking ``jax.Array.is_ready`` probe; backends without it
    report False — unknown counts as busy, so the idle-gap accounting
    under-reports rather than invents idleness)."""
    probe = getattr(window.losses, "is_ready", None)
    if probe is None:
        return False
    try:
        return bool(probe())
    except Exception:
        return False


class WindowPipeline:
    """Free-running multi-window driver over one engine.

    :meth:`run` covers ``n_rounds`` federation rounds in windows of
    ``window`` rounds each, keeping one window in flight ahead of the
    host: window N+1 is DISPATCHED before window N is FINALIZED, so
    the telemetry fan-out, profiler rows and next-window data staging
    all overlap device compute. Results, side effects and bytes match
    a sequential chain of :meth:`FederationEngine.run_rounds` calls
    over the same per-window data.

    Attributes:
        idle_gaps: measured device-idle gap (seconds) before each
            dispatch after the first — the time the device's queue sat
            provably empty while the host prepared the next window
            (see :func:`_outputs_ready`). The ``engine_async`` bench
            tier compares these against the sequential driver's gaps.
        windows_run: dispatched window count from the last :meth:`run`.
    """

    def __init__(self, engine: FederationEngine) -> None:
        self.engine = engine
        self.idle_gaps: list[float] = []
        self.windows_run = 0

    def run(
        self,
        params: Any,
        xs: Any,
        ys: Any,
        weights: Optional[Any] = None,
        epochs: int = 1,
        n_rounds: int = 1,
        window: Optional[int] = None,
        aux: Optional[Any] = None,
        scaffold_state: Optional[tuple[Any, Any]] = None,
        donate: Optional[bool] = None,
        schedule: Optional[FedBuffSchedule] = None,
        data_for: Optional[DataSupplier] = None,
        prefetch: Optional[bool] = None,
        should_stop: Optional[Callable[[], bool]] = None,
    ) -> tuple[Optional[tuple], int]:
        """Run ``n_rounds`` rounds free-running; returns
        ``(result, rounds_done)`` where ``result`` follows
        ``run_rounds``' return conventions for the LAST window (None
        if ``should_stop`` fired before the first dispatch).

        ``window`` (rounds per dispatch) defaults to
        ``Settings.SHARD_ROUNDS_PER_DISPATCH``. ``schedule`` spans the
        FULL run and is carved into per-window slices
        (:meth:`FedBuffSchedule.window`); per-round ``weights``
        ``[n_rounds, n]`` are sliced the same way. ``data_for``
        supplies each window's (possibly reshuffled, mesh-placed) data
        — staged on the :class:`WindowPrefetcher` thread when
        ``prefetch`` (default ``Settings.ENGINE_PREFETCH``) is on, or
        inline otherwise; both stagings are the same pure function of
        the window index, so the knob never changes bytes.
        ``should_stop`` is polled between dispatches (interrupt
        honoring at exactly the sequential driver's granularity)."""
        eng = self.engine
        window = max(
            1,
            int(
                window
                if window is not None
                else Settings.SHARD_ROUNDS_PER_DISPATCH
            ),
        )
        if prefetch is None:
            prefetch = bool(Settings.ENGINE_PREFETCH)
        if schedule is not None and schedule.n_rounds != int(n_rounds):
            raise ValueError(
                f"schedule covers {schedule.n_rounds} rounds for a "
                f"{n_rounds}-round run"
            )
        w = None if weights is None else weights
        per_round_w = getattr(w, "ndim", 1) == 2
        scaffold = scaffold_state is not None
        has_aux = aux is not None

        prefetcher = (
            WindowPrefetcher(data_for)
            if (prefetch and data_for is not None)
            else None
        )
        self.idle_gaps = []
        self.windows_run = 0
        pending: Optional[EngineWindow] = None
        result: Optional[tuple] = None
        done = 0
        widx = 0
        cur_xs, cur_ys = xs, ys
        try:
            while done < int(n_rounds):
                if should_stop is not None and should_stop():
                    break
                k = min(window, int(n_rounds) - done)
                # This window's data: taken from the prefetch thread
                # (staged while the previous window ran) or computed
                # inline — same supplier, same bytes.
                if data_for is not None:
                    staged = (
                        prefetcher.take(widx)
                        if (prefetcher is not None and widx > 0)
                        else data_for(widx, done, k)
                    )
                    if staged is not None:
                        cur_xs, cur_ys = staged
                idle_probe = pending is not None and _outputs_ready(pending)
                t_probe = time.monotonic()
                handle = eng.dispatch_window(
                    params,
                    cur_xs,
                    cur_ys,
                    weights=(w[done:done + k] if per_round_w else w),
                    epochs=epochs,
                    n_rounds=k,
                    aux=aux,
                    scaffold_state=scaffold_state,
                    donate=donate,
                    schedule=(
                        None if schedule is None else schedule.window(done, k)
                    ),
                )
                t_disp = time.monotonic()
                if pending is not None:
                    # Idle-gap accounting: if the previous window's
                    # outputs were ALREADY ready before we started
                    # building this dispatch, the device queue sat
                    # empty at least for the prep sliver we just
                    # measured; otherwise the queue never drained.
                    self.idle_gaps.append(
                        (t_disp - t_probe) if idle_probe else 0.0
                    )
                # Stage the NEXT window's data while the device works
                # and before this host thread dives into finalize.
                nxt = done + k
                if prefetcher is not None and nxt < int(n_rounds):
                    prefetcher.start(
                        widx + 1, nxt, min(window, int(n_rounds) - nxt)
                    )
                if pending is not None:
                    # Window N's host leg (telemetry replay, profiler
                    # rows) overlaps window N+1's device leg.
                    result = pending.finalize()
                # Chain the output futures straight into the next
                # dispatch — the double buffer: with donation on these
                # are the only live copy of the federation state.
                params = handle.params
                if scaffold:
                    aux = handle.aux
                    scaffold_state = handle.scaffold_state
                elif has_aux:
                    aux = handle.aux
                pending = handle
                done += k
                widx += 1
                self.windows_run += 1
        finally:
            if prefetcher is not None:
                prefetcher.close()
            if pending is not None:
                result = pending.finalize()
        if self.idle_gaps:
            metrics.gauge(
                "tpfl_engine_idle_gap_seconds",
                float(sum(self.idle_gaps) / len(self.idle_gaps)),
                labels={"driver": "pipeline"},
            )
        return result, done
