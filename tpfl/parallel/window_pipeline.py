"""WindowPipeline — the free-running engine driver (Sebulba split).

Podracer's Sebulba architecture (PAPERS.md) pins host work and device
compute to SEPARATE streams and double-buffers between them. The fused
engine already compiles K federation rounds into one device dispatch
(:class:`~tpfl.parallel.engine.FederationEngine`), but a sequential
driver still pays, BETWEEN windows, the host-side costs the device
never needed to wait for: the telemetry fan-out
(``engine_obs.replay_window``), profiler bookkeeping, next-window data
staging, and the dispatch RTT itself.

This driver exploits what JAX gives for free — async dispatch (a
program call returns output FUTURES while the device works) and buffer
donation (window N+1 consumes window N's output buffers in place) — to
run the engine free:

::

    device |  win N  ||  win N+1  ||  win N+2  | ...
    host   | dispatch N+1 ; finalize N (telemetry replay, profiler)
           | stage N+2's data on the prefetch thread ; dispatch N+2 ...

Steady state: the device's dispatch queue is never empty, so dispatch
RTT and host work vanish from wall clock; the measured inter-window
device-idle gap (:attr:`WindowPipeline.idle_gaps`, fed from the
``jax.Array.is_ready`` probe before each dispatch) collapses to the
argument-prep sliver — the ``engine_async`` bench tier gates the ≥2x
cut vs sequential dispatch.

Determinism: the pipeline reorders HOST work only — the device sees
the identical program sequence over the identical buffers, so
same-seed runs stay byte-identical to chained
``FederationEngine.run_rounds`` calls (tests/test_engine_async.py
proves it at 1 and 8 devices, donation report still clean).

Double-buffer ownership: with donation on, window N's input state is
consumed by the device program; the ONLY live copy of the federation
state is window N's output futures, which this driver chains straight
into window N+1's dispatch. At most two windows are ever in flight, so
at most two state buffers exist — the explicit double buffer.

Concurrency: the prefetch thread (:class:`WindowPrefetcher`) is a
named, single-slot stager guarded by ``tpfl.concurrency.make_lock``
(deadlock-ordering tracked under ``LOCK_TRACING``); it is joined at
every take and on shutdown — no thread outlives :meth:`run`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from tpfl import concurrency
from tpfl.management.telemetry import metrics
from tpfl.parallel.engine import (
    EngineWindow,
    FederationEngine,
    FedBuffSchedule,
    start_host_copy,
)
from tpfl.settings import Settings

# data_for(window_index, start_round, n_rounds) -> (xs, ys) or None
# (None = reuse the current window's arrays).
DataSupplier = Callable[[int, int, int], "Optional[tuple[Any, Any]]"]

# Live pipelines by owner addr — the shutdown seam: Node.stop and
# FaultInjector.crash interrupt a node's in-flight run via
# :func:`interrupt_for` so donated buffers retire cleanly instead of
# racing the teardown.
# guarded-by: _ACTIVE_LOCK
_ACTIVE: "dict[str, WindowPipeline]" = {}
_ACTIVE_LOCK = concurrency.make_lock("window_pipeline._ACTIVE_LOCK")


def interrupt_for(addr: str) -> bool:
    """Interrupt the pipeline currently running for ``addr`` (no-op
    False when none is registered). The run finishes its in-flight
    window, finalizes or abandons the handle, and returns — callers
    (Node.stop, FaultInjector.crash) get a clean join point instead of
    leaked prefetch threads and unreferenced donated buffers."""
    with _ACTIVE_LOCK:
        pipe = _ACTIVE.get(addr)
    if pipe is None:
        return False
    pipe.interrupt()
    return True


class WindowPrefetcher:
    """Single-slot background stager for the next window's data.

    One named thread per window: :meth:`start` launches it to run the
    supplier (shuffle + ``device_put`` placement — pure host/transfer
    work), :meth:`take` joins it and hands the staged arrays over. The
    slot is guarded by a :func:`tpfl.concurrency.make_lock` lock, and
    a thread is ALWAYS joined before the next starts and on
    :meth:`close` — the pipeline leaks no threads past its run.
    """

    def __init__(
        self, fn: DataSupplier, name: str = "tpfl-window-prefetch"
    ) -> None:
        self._fn = fn
        self._name = name
        self._lock = concurrency.make_lock("WindowPrefetcher._lock")
        # ephemeral: live thread handle — always joined before the next
        # stage and on close(); nothing to resume.
        self._thread: Optional[threading.Thread] = None
        # guarded-by: _lock — (window_index, staged_data, error)
        # ephemeral: in-flight staged data — re-staged from the data
        # supplier on the next run; device buffers cannot checkpoint.
        self._slot: Optional[tuple] = None

    def start(self, widx: int, start_round: int, n_rounds: int) -> None:
        """Stage window ``widx``'s data in the background (joins any
        previous stage first — one in flight)."""
        self.close()

        def work() -> None:
            out, err = None, None
            try:
                out = self._fn(widx, start_round, n_rounds)
            except BaseException as e:  # surfaced at take()
                err = e
            with self._lock:
                self._slot = (widx, out, err)

        self._thread = threading.Thread(
            target=work, name=f"{self._name}[{widx}]", daemon=True
        )
        self._thread.start()

    def take(self, widx: int) -> "Optional[tuple[Any, Any]]":
        """Join the stage and return window ``widx``'s staged data
        (None when nothing was staged for it); re-raises a supplier
        error on the caller's thread."""
        self.close()
        with self._lock:
            slot, self._slot = self._slot, None
        if slot is None:
            return None
        staged_widx, out, err = slot
        if err is not None:
            raise err
        return out if staged_widx == widx else None

    def close(self) -> None:
        """Join any in-flight stage (idempotent)."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()


def _outputs_ready(window: EngineWindow) -> bool:
    """True when the window's device work has provably completed (the
    non-blocking ``jax.Array.is_ready`` probe; backends without it
    report False — unknown counts as busy, so the idle-gap accounting
    under-reports rather than invents idleness)."""
    probe = getattr(window.losses, "is_ready", None)
    if probe is None:
        return False
    try:
        return bool(probe())
    except Exception:
        return False


class WindowPipeline:
    """Free-running multi-window driver over one engine.

    :meth:`run` covers ``n_rounds`` federation rounds in windows of
    ``window`` rounds each, keeping one window in flight ahead of the
    host: window N+1 is DISPATCHED before window N is FINALIZED, so
    the telemetry fan-out, profiler rows and next-window data staging
    all overlap device compute. Results, side effects and bytes match
    a sequential chain of :meth:`FederationEngine.run_rounds` calls
    over the same per-window data.

    Attributes:
        idle_gaps: measured device-idle gap (seconds) before each
            dispatch after the first — the time the device's queue sat
            provably empty while the host prepared the next window
            (see :func:`_outputs_ready`). The ``engine_async`` bench
            tier compares these against the sequential driver's gaps.
        windows_run: dispatched window count from the last :meth:`run`.
    """

    def __init__(self, engine: FederationEngine) -> None:
        self.engine = engine
        # unguarded: written only by the run() thread; cross-thread
        # readers (bench/tests) read after run() returns.
        # ephemeral: per-run diagnostics — every run() resets them; the
        # durable cadence state rides the engine snapshot
        # (_materialize_snapshot -> engine.export_state).
        self.idle_gaps: list[float] = []
        # ephemeral: per-run diagnostics (see idle_gaps).
        self.windows_run = 0
        # Cross-thread stop flag (interrupt_for / Node.stop) — honored
        # at exactly the between-dispatch granularity should_stop is.
        # ephemeral: live control signal — a resumed run starts
        # unaborted by construction.
        self._abort = threading.Event()

    def interrupt(self) -> None:
        """Request the current :meth:`run` stop at the next window
        boundary (thread-safe; sticky until the next run starts)."""
        self._abort.set()

    def _materialize_snapshot(
        self, snap: tuple, snapshot_to: Callable[[int, dict], None]
    ) -> None:
        """Consume a pending cadence snapshot: the D2H copies started
        at dispatch have had a full device window to land, so the
        ``np.asarray`` inside ``export_state`` reads host memory. The
        engine's ``_rounds_done`` already equals the snapshotted
        window's position here (it advances at dispatch, and the next
        dispatch hasn't happened yet) — ``rounds_at`` pins it anyway."""
        rounds_at, p, a, ss = snap
        state = self.engine.export_state(p, aux=a, scaffold_state=ss)
        state["rounds_done"] = int(rounds_at)
        snapshot_to(int(rounds_at), state)

    def run(
        self,
        params: Any,
        xs: Any,
        ys: Any,
        weights: Optional[Any] = None,
        epochs: int = 1,
        n_rounds: int = 1,
        window: Optional[int] = None,
        aux: Optional[Any] = None,
        scaffold_state: Optional[tuple[Any, Any]] = None,
        donate: Optional[bool] = None,
        schedule: Optional[FedBuffSchedule] = None,
        data_for: Optional[DataSupplier] = None,
        prefetch: Optional[bool] = None,
        should_stop: Optional[Callable[[], bool]] = None,
        weights_for: Optional[Callable[[int], Any]] = None,
        snapshot_every: int = 0,
        snapshot_to: Optional[Callable[[int, dict], None]] = None,
        owner: Optional[str] = None,
    ) -> tuple[Optional[tuple], int]:
        """Run ``n_rounds`` rounds free-running; returns
        ``(result, rounds_done)`` where ``result`` follows
        ``run_rounds``' return conventions for the LAST window (None
        if ``should_stop`` fired before the first dispatch).

        ``window`` (rounds per dispatch) defaults to
        ``Settings.SHARD_ROUNDS_PER_DISPATCH``. ``schedule`` spans the
        FULL run and is carved into per-window slices
        (:meth:`FedBuffSchedule.window`); per-round ``weights``
        ``[n_rounds, n]`` are sliced the same way. ``data_for``
        supplies each window's (possibly reshuffled, mesh-placed) data
        — staged on the :class:`WindowPrefetcher` thread when
        ``prefetch`` (default ``Settings.ENGINE_PREFETCH``) is on, or
        inline otherwise; both stagings are the same pure function of
        the window index, so the knob never changes bytes.
        ``should_stop`` is polled between dispatches (interrupt
        honoring at exactly the sequential driver's granularity).

        ISSUE-17 elastic hooks: ``weights_for(widx)`` supplies each
        window's fold-weight vector — the membership re-mask seam
        (churn between windows edits weights only; the compiled
        program and its shapes never move), overriding ``weights``
        when given. ``snapshot_every``/``snapshot_to`` arm cadence
        checkpointing: every K-th window's output state is snapshotted
        OFF the critical path — the D2H copy starts non-blocking at
        dispatch (:func:`~tpfl.parallel.engine.start_host_copy`) and
        materializes at the NEXT loop top, before the dispatch that
        would donate those buffers away, so the device pipeline never
        stalls on checkpoint I/O. ``snapshot_to(rounds_done, state)``
        receives :meth:`FederationEngine.export_state` output.
        ``owner`` registers this run for :func:`interrupt_for`."""
        eng = self.engine
        window = max(
            1,
            int(
                window
                if window is not None
                else Settings.SHARD_ROUNDS_PER_DISPATCH
            ),
        )
        if prefetch is None:
            prefetch = bool(Settings.ENGINE_PREFETCH)
        if schedule is not None and schedule.n_rounds != int(n_rounds):
            raise ValueError(
                f"schedule covers {schedule.n_rounds} rounds for a "
                f"{n_rounds}-round run"
            )
        w = None if weights is None else weights
        per_round_w = getattr(w, "ndim", 1) == 2
        scaffold = scaffold_state is not None
        has_aux = aux is not None

        prefetcher = (
            WindowPrefetcher(data_for)
            if (prefetch and data_for is not None)
            else None
        )
        self.idle_gaps = []
        self.windows_run = 0
        self._abort.clear()
        if owner is not None:
            with _ACTIVE_LOCK:
                _ACTIVE[owner] = self
        snap_every = max(0, int(snapshot_every)) if snapshot_to else 0
        # (rounds_done_after_window, params, aux, scaffold_state) of a
        # window whose host copy is in flight; materialized at the next
        # loop top, BEFORE the dispatch that donates those buffers.
        snap_pending: Optional[tuple] = None
        pending: Optional[EngineWindow] = None
        result: Optional[tuple] = None
        done = 0
        widx = 0
        cur_xs, cur_ys = xs, ys
        try:
            while done < int(n_rounds):
                if snap_pending is not None:
                    self._materialize_snapshot(snap_pending, snapshot_to)
                    snap_pending = None
                if self._abort.is_set() or (
                    should_stop is not None and should_stop()
                ):
                    break
                k = min(window, int(n_rounds) - done)
                if weights_for is not None:
                    # The elastic re-mask seam: membership churn since
                    # the last window lands here as a weight-vector
                    # edit — same program, same shapes, zero recompile.
                    w = weights_for(widx)
                    per_round_w = getattr(w, "ndim", 1) == 2
                # This window's data: taken from the prefetch thread
                # (staged while the previous window ran) or computed
                # inline — same supplier, same bytes.
                if data_for is not None:
                    staged = (
                        prefetcher.take(widx)
                        if (prefetcher is not None and widx > 0)
                        else data_for(widx, done, k)
                    )
                    if staged is not None:
                        cur_xs, cur_ys = staged
                idle_probe = pending is not None and _outputs_ready(pending)
                t_probe = time.monotonic()
                handle = eng.dispatch_window(
                    params,
                    cur_xs,
                    cur_ys,
                    weights=(w[done:done + k] if per_round_w else w),
                    epochs=epochs,
                    n_rounds=k,
                    aux=aux,
                    scaffold_state=scaffold_state,
                    donate=donate,
                    schedule=(
                        None if schedule is None else schedule.window(done, k)
                    ),
                )
                t_disp = time.monotonic()
                if pending is not None:
                    # Idle-gap accounting: if the previous window's
                    # outputs were ALREADY ready before we started
                    # building this dispatch, the device queue sat
                    # empty at least for the prep sliver we just
                    # measured; otherwise the queue never drained.
                    self.idle_gaps.append(
                        (t_disp - t_probe) if idle_probe else 0.0
                    )
                # Stage the NEXT window's data while the device works
                # and before this host thread dives into finalize.
                nxt = done + k
                if prefetcher is not None and nxt < int(n_rounds):
                    prefetcher.start(
                        widx + 1, nxt, min(window, int(n_rounds) - nxt)
                    )
                if pending is not None:
                    # Window N's host leg (telemetry replay, profiler
                    # rows) overlaps window N+1's device leg.
                    result = pending.finalize()
                # Chain the output futures straight into the next
                # dispatch — the double buffer: with donation on these
                # are the only live copy of the federation state.
                params = handle.params
                if scaffold:
                    aux = handle.aux
                    scaffold_state = handle.scaffold_state
                elif has_aux:
                    aux = handle.aux
                pending = handle
                done += k
                widx += 1
                self.windows_run += 1
                if snap_every and widx % snap_every == 0:
                    # Cadence checkpoint: start the non-blocking D2H
                    # copy NOW (it completes while the device runs this
                    # window); np.asarray at the next loop top reads
                    # host memory — the copy_to_host_async host leg.
                    start_host_copy(params)
                    if aux is not None:
                        start_host_copy(aux)
                    if scaffold:
                        start_host_copy(scaffold_state)
                    snap_pending = (
                        done,
                        params,
                        aux,
                        scaffold_state if scaffold else None,
                    )
        finally:
            if owner is not None:
                with _ACTIVE_LOCK:
                    if _ACTIVE.get(owner) is self:
                        del _ACTIVE[owner]
            if prefetcher is not None:
                prefetcher.close()
            if pending is not None:
                if self._abort.is_set():
                    # Interrupted shutdown (Node.stop / fault injector):
                    # retire the donated buffers without the telemetry
                    # fan-out — the handle must not outlive the run.
                    pending.abandon()
                    result = None
                else:
                    result = pending.finalize()
        if snap_pending is not None and not self._abort.is_set():
            # The run ended with a copy still in flight (final window
            # hit the cadence): no further dispatch will donate these
            # buffers, so materializing here is safe and loses nothing.
            self._materialize_snapshot(snap_pending, snapshot_to)
        if self.idle_gaps:
            metrics.gauge(
                "tpfl_engine_idle_gap_seconds",
                float(sum(self.idle_gaps) / len(self.idle_gaps)),
                labels={"driver": "pipeline"},
            )
        return result, done
