"""Cross-device population tier: 1M registered clients, K resident.

The pfl-research / PeerFL shape of federated learning (PAPERS.md):
a huge census of REGISTERED, mostly-offline leaf clients, of which
only K ≈ 100 participate in any round. tpfl's engine already has the
two kernels this needs — :func:`~tpfl.parallel.engine
.sample_participants` (the seeded per-round cohort draw) and
:meth:`~tpfl.parallel.engine.FederationEngine.broadcast_params` (stack
K working rows from the ONE persistent global model) — this module
adds the bookkeeping around them:

- :class:`ClientPopulation` — the census. Holds ONLY O(active) state:
  the persistent model lives in the engine (one model, not N), and
  per-client records exist solely for clients that have actually
  participated (a dict that grows with touched clients, never with
  the census). Registering 1M clients costs a handful of ints.
- **Two-level topology** — the engine's resident nodes are EDGE
  AGGREGATORS: they gossip P2P over the mesh (the engine's fold — over
  ``nodes`` on ICI and ``hosts`` on DCN), while sampled leaf clients
  attach to edges by :meth:`edge_assignment` for the round. A round is
  therefore leaf→edge intake (the sampled cohort trains as the
  engine's node rows) + the edges' P2P fold.
- **Straggler cutoffs** — :meth:`round_weights` zeroes a seeded
  fraction of the cohort exactly like quorum degradation (a w=0 row
  is ignored by the masked fold, bit-for-bit), and
  :meth:`straggler_schedule` lowers the same skew to a
  :class:`~tpfl.parallel.engine.FedBuffSchedule` so late clients fold
  staleness-weighted instead of dropping.
- **Checkpointing** — :meth:`state_export` / :meth:`state_import`
  round-trip through :class:`~tpfl.management.checkpoint
  .EngineCheckpointer` via ``FederationEngine.export_state`` (which
  includes an attached population automatically). The snapshot is
  O(touched clients): sampled clients' records restore exactly;
  never-sampled clients have no state to restore.

See docs/scaling.md "Cross-device population tier".
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from tpfl.learning.serialization import leaf_bytes
from tpfl.management import fleetobs
from tpfl.parallel.engine import FedBuffSchedule, sample_participants
from tpfl.settings import Settings

__all__ = ["ClientPopulation"]


class ClientPopulation:
    """A registered cross-device census sampling K participants/round.

    ``registered`` / ``sample`` default to
    ``Settings.POPULATION_CLIENTS`` / ``Settings.POPULATION_SAMPLE``;
    ``seed`` keys every draw — same census, same seed, same round ⇒
    the same cohort, byte for byte (the engine's determinism
    discipline extended over sampling). ``self.round`` is the
    population's own round cursor, advanced by
    :meth:`complete_round` and restored by checkpoints.
    """

    def __init__(
        self,
        registered: Optional[int] = None,
        sample: Optional[int] = None,
        seed: int = 0,
    ) -> None:
        self.registered = int(
            registered
            if registered is not None
            else Settings.POPULATION_CLIENTS
        )
        self.sample = int(
            sample if sample is not None else Settings.POPULATION_SAMPLE
        )
        if self.registered <= 0:
            raise ValueError(
                f"population needs registered > 0, got {self.registered} "
                f"(set Settings.POPULATION_CLIENTS or pass registered=)"
            )
        if not (0 < self.sample <= self.registered):
            raise ValueError(
                f"cannot sample {self.sample} of {self.registered} "
                f"registered clients"
            )
        self.seed = int(seed)
        self.round = 0
        # O(touched), never O(registered): a record exists only once a
        # client has folded. int keys in memory; stringified for the
        # msgpack checkpoint (state_export).
        self.clients: dict[int, dict] = {}
        # The ONE allowed O(census) structure (ISSUE-20): a coverage
        # BITSET — one bit per registered client, set the first time
        # the sampler reaches it. 1M census = 125 KB; everything else
        # in the observatory stays O(1)/O(touched).
        self._coverage = np.zeros((self.registered + 7) // 8, np.uint8)
        # ephemeral: derived sketch — the coverage bitset's popcount,
        # recomputed exactly from the exported bitset on import.
        self._sampled_count = 0
        # ephemeral: derived sketch — Jain-fairness Σ rounds over
        # touched clients, recomputed from the clients dict on import.
        self._part_sum = 0
        # ephemeral: derived sketch — Jain-fairness Σ rounds² over
        # touched clients, recomputed from the clients dict on import.
        self._part_sumsq = 0
        # ephemeral: runtime binding — re-established by bind() when
        # the restored population re-attaches (import_state calls it).
        self._engine: Optional[Any] = None

    # --- engine binding ---------------------------------------------------

    def bind(self, engine: Any) -> None:
        """Called by ``FederationEngine.attach_population``: remember
        the engine whose resident nodes serve as this population's
        edge aggregators. The engine's node axis is the round's
        working set — it must hold the sampled cohort."""
        if engine is not None and self.sample > int(engine.n_nodes):
            raise ValueError(
                f"sampled cohort of {self.sample} does not fit the "
                f"engine's {engine.n_nodes} node rows"
            )
        self._engine = engine

    # --- the per-round cycle ----------------------------------------------

    def begin_round(self, round: Optional[int] = None) -> np.ndarray:
        """The round's cohort: ``sample`` distinct client ids drawn
        from the census, seeded by ``(seed, round)`` — recomputable at
        any time (resume re-draws the same cohort from the restored
        round cursor)."""
        r = self.round if round is None else int(round)
        return sample_participants(self.registered, self.sample, self.seed, r)

    def edge_assignment(
        self, ids: Any, n_edges: Optional[int] = None
    ) -> np.ndarray:
        """Edge-aggregator index per sampled client — the two-level
        topology's attach step. Round-robin over the cohort's sorted
        order: deterministic, and balanced to within one client per
        edge. ``n_edges`` defaults to the bound engine's logical node
        count (every resident node serves as an edge)."""
        if n_edges is None:
            if self._engine is None:
                raise ValueError(
                    "edge_assignment needs n_edges= or a bound engine"
                )
            n_edges = int(self._engine.n_nodes)
        ids = np.asarray(ids)
        return np.arange(ids.shape[0]) % max(1, int(n_edges))

    def round_weights(
        self,
        ids: Any,
        cutoff_frac: float = 0.0,
        round: Optional[int] = None,
    ) -> np.ndarray:
        """[K] fold weights for the cohort with a seeded
        ``cutoff_frac`` of stragglers ZEROED — the quorum-degradation
        reuse: a cut client's row rides the dispatch untouched and the
        masked fold ignores it exactly, so the straggler cutoff costs
        no recompile and no shape change. At least one client always
        survives (an all-zero round would re-enter the uniform
        fallback with semantics no cross-device tier wants)."""
        ids = np.asarray(ids)
        k = int(ids.shape[0])
        w = np.ones((k,), np.float32)
        frac = float(cutoff_frac)
        if frac <= 0.0:
            return w
        r = self.round if round is None else int(round)
        n_cut = min(int(frac * k), k - 1)
        if n_cut > 0:
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, r, 1])
            )
            w[rng.choice(k, size=n_cut, replace=False)] = 0.0
        return w

    def straggler_schedule(
        self,
        n_rounds: int,
        straggler_frac: float = 0.25,
        max_staleness: int = 2,
        start_round: Optional[int] = None,
    ) -> FedBuffSchedule:
        """The FedBuff path for the cohort: a seeded
        ``straggler_frac`` of the K participants run on longer arrival
        periods (up to ``max_staleness + 1`` rounds), so their
        contributions fold late and staleness-weighted instead of
        dropping — :meth:`FedBuffSchedule.from_periods` over the
        sampled cohort, with the population's seed/round keying the
        draw."""
        r0 = self.round if start_round is None else int(start_round)
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, r0, 2])
        )
        periods = np.ones((self.sample,), np.int64)
        n_slow = min(int(float(straggler_frac) * self.sample),
                     self.sample - 1)
        if n_slow > 0:
            slow = rng.choice(self.sample, size=n_slow, replace=False)
            periods[slow] = rng.integers(
                2, max(2, int(max_staleness) + 1) + 1, size=n_slow
            )
        return FedBuffSchedule.from_periods(
            periods, int(n_rounds), start_round=r0
        )

    def complete_round(
        self,
        ids: Any,
        weights: Optional[Any] = None,
        losses: Optional[Any] = None,
    ) -> None:
        """Commit one round: advance the round cursor and the folded
        clients' records (stragglers — w=0 rows — do not advance:
        their contribution never folded). ``losses`` (optional,
        positionally aligned with ``ids``) lands in each record as
        the client's last observed loss.

        The commit walk doubles as the population observatory's
        sampling point (ISSUE-20): every sampled id — cut or not —
        sets its coverage bit (the sampler REACHED it), each folding
        client's staleness gap (rounds since it last folded, 0 for a
        first participation) is captured before its record advances,
        and the Jain-fairness partial sums track the fold-count bump
        in O(1). The round's sketch then fans out through
        :func:`tpfl.management.fleetobs.population_round` as
        ``tpfl_pop_*`` series + one ``population_round`` flight event
        — all O(touched) work the walk was already paying for."""
        ids = np.asarray(ids, np.int64)
        w = (
            np.ones((ids.shape[0],), np.float32)
            if weights is None
            else np.asarray(weights, np.float32)
        )
        # Coverage: vectorized bitset update. Sampled ids are distinct
        # (sample without replacement) so distinct (byte, bit) pairs —
        # the pre-update gather counts newly-reached clients exactly;
        # bitwise_or.at accumulates correctly when ids share a byte.
        if ids.size:
            byte_idx = ids >> 3
            bit = (np.uint8(1) << (ids & 7).astype(np.uint8))
            old = self._coverage[byte_idx]
            self._sampled_count += int(np.count_nonzero((old & bit) == 0))
            np.bitwise_or.at(self._coverage, byte_idx, bit)
        staleness: list[float] = []
        folded = 0
        for pos, cid in enumerate(ids):
            if w[pos] <= 0:
                continue
            folded += 1
            rec = self.clients.setdefault(
                int(cid), {"rounds": 0, "last_round": -1, "loss": 0.0}
            )
            prior = int(rec["rounds"])
            staleness.append(
                float(self.round - int(rec["last_round"])) if prior else 0.0
            )
            # Fairness partial sums: rounds c -> c+1 moves Σc by 1 and
            # Σc² by 2c+1 — Jain's index stays an O(1) read.
            self._part_sum += 1
            self._part_sumsq += 2 * prior + 1
            rec["rounds"] = prior + 1
            rec["last_round"] = int(self.round)
            if losses is not None:
                rec["loss"] = float(np.asarray(losses)[pos])
        committed = int(self.round)
        self.round += 1
        fleetobs.population_round(
            "population",
            round=committed,
            census=self.registered,
            sampled=int(ids.shape[0]),
            folded=folded,
            cut=int(ids.shape[0]) - folded,
            touched=len(self.clients),
            coverage=self.coverage,
            fairness=self.fairness,
            staleness=staleness,
        )

    @property
    def touched(self) -> int:
        """Clients that have ever folded — the snapshot's size."""
        return len(self.clients)

    @property
    def coverage(self) -> float:
        """Fraction of the census the sampler has EVER reached (the
        coverage bitset's popcount over ``registered``) — cut clients
        count: they were drawn, only their fold was dropped."""
        return self._sampled_count / float(self.registered)

    @property
    def fairness(self) -> float:
        """Jain's index over touched clients' participation counts:
        ``(Σc)² / (touched · Σc²)`` — 1.0 is perfectly even service,
        →1/touched is one client hoarding every fold. 1.0 for an
        untouched census (no service yet = no unfairness yet)."""
        if not self.clients or self._part_sumsq == 0:
            return 1.0
        return (self._part_sum * self._part_sum) / (
            len(self.clients) * float(self._part_sumsq)
        )

    # --- checkpoint state -------------------------------------------------

    def state_export(self) -> dict:
        """O(touched) snapshot (msgpack-safe: client ids stringify —
        flax's serializer requires str keys)."""
        return {
            "registered": int(self.registered),
            "sample": int(self.sample),
            "seed": int(self.seed),
            "round": int(self.round),
            # The coverage bitset rides as raw bytes (msgpack bin,
            # 125 KB at a 1M census) — bytes, not ndarray, so the
            # snapshot dict stays ==-comparable for contract checks.
            "coverage": bytes(leaf_bytes(self._coverage)),
            "clients": {
                str(cid): {
                    "rounds": int(rec["rounds"]),
                    "last_round": int(rec["last_round"]),
                    "loss": float(rec["loss"]),
                }
                for cid, rec in self.clients.items()
            },
        }

    def state_import(self, state: dict) -> None:
        self.registered = int(state["registered"])
        self.sample = int(state["sample"])
        self.seed = int(state["seed"])
        self.round = int(state["round"])
        self.clients = {
            int(cid): {
                "rounds": int(rec["rounds"]),
                "last_round": int(rec["last_round"]),
                "loss": float(rec["loss"]),
            }
            for cid, rec in dict(state.get("clients", {})).items()
        }
        n_bytes = (self.registered + 7) // 8
        cov = state.get("coverage")
        if cov is not None:
            self._coverage = np.zeros(n_bytes, np.uint8)
            arr = (
                np.frombuffer(cov, np.uint8)
                if isinstance(cov, (bytes, bytearray))
                else np.asarray(cov, np.uint8).ravel()
            )
            self._coverage[: min(arr.size, n_bytes)] = arr[:n_bytes]
        else:
            # Pre-ISSUE-20 checkpoint: best-effort rebuild — folded
            # clients were certainly sampled; cut-only clients are
            # unrecoverable, so coverage restores as a lower bound.
            self._coverage = np.zeros(n_bytes, np.uint8)
            for cid in self.clients:
                self._coverage[cid >> 3] |= np.uint8(1 << (cid & 7))
        # Derived sketches recompute exactly from the restored state.
        self._sampled_count = int(np.unpackbits(self._coverage).sum())
        self._part_sum = sum(
            int(rec["rounds"]) for rec in self.clients.values()
        )
        self._part_sumsq = sum(
            int(rec["rounds"]) ** 2 for rec in self.clients.values()
        )

    @classmethod
    def from_state(cls, state: dict) -> "ClientPopulation":
        pop = cls(
            registered=int(state["registered"]),
            sample=int(state["sample"]),
            seed=int(state["seed"]),
        )
        pop.state_import(state)
        return pop
