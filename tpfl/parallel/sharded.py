"""ShardedTrainer — dp/FSDP training of ONE large model over the mesh.

The reference has no intra-learner parallelism at all (SURVEY §2.10:
Lightning single-process, ``torch.set_num_threads(1)``). This is the
TPU-idiomatic seam: a jitted train step whose batch is sharded over a
``dp`` axis and (optionally) whose parameters/optimizer state are
sharded FSDP-style; XLA inserts the gradient all-reduce / all-gather
collectives over ICI. Plugs into a Learner via ``optimizer_factory`` /
custom fit, or is used directly by benchmarks.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpfl.learning.jax_learner import cross_entropy_loss, default_optimizer


def fsdp_spec(leaf: Any, axis: str, axis_size: int) -> PartitionSpec:
    """Per-leaf FSDP heuristic: shard the last divisible dim; replicate
    small/indivisible leaves.

    Why the LAST dim: any dim gives the same 1/axis_size storage, but
    kernels are [..., in, out] and the backward w.r.t. activations
    contracts over ``out`` — with ``out`` sharded, XLA resolves the
    cotangent with an all-reduce and it comes out replicated, so a
    following transpose/reshape (e.g. the CNN flatten's transpose)
    re-shards cleanly to batch sharding. Sharding ``in`` instead leaves
    cotangents feature-sharded and triggered XLA's "[SPMD] Involuntary
    full rematerialization" on the flatten reshape (seen in round 2's
    MULTICHIP log)."""
    shape = np.shape(leaf)
    if not shape:
        return PartitionSpec()
    for i in reversed(range(len(shape))):
        if shape[i] % axis_size == 0 and shape[i] >= axis_size:
            spec = [None] * len(shape)
            spec[i] = axis
            return PartitionSpec(*spec)
    return PartitionSpec()


class ShardedTrainer:
    """Data-parallel (+ optional FSDP) single-model training.

    Args:
        module: flax module.
        mesh: Mesh with a ``dp`` axis (at least).
        fsdp: shard params/opt-state over the dp axis per-leaf.
        learning_rate / optimizer_factory / loss_fn: as JaxLearner.
    """

    def __init__(
        self,
        module: Any,
        mesh: Mesh,
        fsdp: bool = False,
        learning_rate: float = 0.1,
        optimizer_factory: Optional[Callable] = None,
        loss_fn: Optional[Callable] = None,
        seed: int = 0,
    ) -> None:
        self.module = module
        self.mesh = mesh
        self.fsdp = fsdp
        self.axis = "dp"
        self._opt = (optimizer_factory or default_optimizer)(learning_rate)
        self._loss_fn = loss_fn or cross_entropy_loss
        self.seed = seed
        self._step_fn: Optional[Callable] = None
        self._step_aux_fn: Optional[Callable] = None

    # --- setup ---

    def _param_sharding(self, params: Any) -> Any:
        axis_size = self.mesh.shape[self.axis]
        if self.fsdp:
            return jax.tree_util.tree_map(
                lambda p: NamedSharding(
                    self.mesh, fsdp_spec(p, self.axis, axis_size)
                ),
                params,
            )
        return jax.tree_util.tree_map(
            lambda p: NamedSharding(self.mesh, PartitionSpec()), params
        )

    def init(self, input_shape: tuple[int, ...]) -> tuple[Any, Any]:
        """(params, opt_state), placed on the mesh (aux-free modules;
        BatchNorm'd models use :meth:`init_with_aux`)."""
        params, aux, opt_state = self.init_with_aux(input_shape)
        if aux:
            raise ValueError(
                f"Module has mutable collections {sorted(aux)} — use "
                f"init_with_aux() and train_step_with_aux()."
            )
        return params, opt_state

    def init_with_aux(self, input_shape: tuple[int, ...]) -> tuple[Any, Any, Any]:
        """(params, aux, opt_state), placed on the mesh. ``aux`` holds
        mutable collections (``batch_stats`` for BatchNorm models like
        ResNet18), replicated across the mesh — stats are small and are
        updated by the same replicated computation on every shard."""
        dummy = jnp.zeros((1, *input_shape), jnp.float32)
        variables = self.module.init(
            jax.random.PRNGKey(self.seed), dummy, train=False
        )
        params = variables["params"]
        aux = {k: v for k, v in variables.items() if k != "params"}
        params = jax.device_put(params, self._param_sharding(params))
        if aux:
            rep = NamedSharding(self.mesh, PartitionSpec())
            aux = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, rep), aux
            )
        opt_state = self._opt.init(params)
        return params, aux, opt_state

    def shard_batch(self, x: Any, y: Any) -> tuple[Any, Any]:
        """Shard the batch dimension over dp."""
        sh = NamedSharding(self.mesh, PartitionSpec(self.axis))
        return jax.device_put(jnp.asarray(x), sh), jax.device_put(
            jnp.asarray(y), sh
        )

    # --- step ---

    def _gather_for_compute(self, p: Any) -> Any:
        """ZeRO-3 semantics for FSDP: gather the sharded weights for
        compute (all-gather, O(params)) and keep the activations
        batch-sharded. Without this, GSPMD computes WITH sharded
        weights — tensor-parallel style — and re-shards ACTIVATIONS
        between layers, moving O(batch) bytes per step (caught by
        tests/test_scaling_model.py). The constraint's transpose
        reduce-scatters the grads back to the param sharding. No-op
        when fsdp is off (params already replicated)."""
        if not self.fsdp:
            return p
        replicated = NamedSharding(self.mesh, PartitionSpec())
        return jax.lax.with_sharding_constraint(
            p, jax.tree_util.tree_map(lambda _: replicated, p)
        )

    def _build_step(self, params: Any) -> Callable:
        module = self.module
        loss_fn = self._loss_fn
        opt = self._opt
        param_sh = self._param_sharding(params)
        batch_sh = NamedSharding(self.mesh, PartitionSpec(self.axis))

        gather = self._gather_for_compute

        def step(params, opt_state, x, y):
            def loss_of(p):
                p = gather(p)
                logits = module.apply({"params": p}, x, train=False)
                return loss_fn(logits, y).mean()

            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        # in/out shardings pin the layout; XLA inserts the collectives
        # (grad all-reduce over dp; FSDP gather/scatter when params are
        # sharded).
        return jax.jit(
            step,
            donate_argnums=(0, 1),
            in_shardings=(
                param_sh,
                None,  # opt state: let XLA mirror the param layout
                batch_sh,
                batch_sh,
            ),
            out_shardings=None,
        )

    def train_step(
        self, params: Any, opt_state: Any, x: Any, y: Any
    ) -> tuple[Any, Any, Any]:
        if self._step_fn is None:
            self._step_fn = self._build_step(params)
        return self._step_fn(params, opt_state, x, y)

    # --- aux-threaded variant (BatchNorm models) ---

    def _build_step_aux(self, params: Any) -> Callable:
        module = self.module
        loss_fn = self._loss_fn
        opt = self._opt
        param_sh = self._param_sharding(params)
        batch_sh = NamedSharding(self.mesh, PartitionSpec(self.axis))

        gather = self._gather_for_compute

        def step(params, aux, opt_state, x, y):
            def loss_of(p):
                p = gather(p)  # ZeRO-3 gather — see _gather_for_compute
                logits, new_aux = module.apply(
                    {"params": p, **aux}, x, train=True, mutable=list(aux)
                )
                return loss_fn(logits, y).mean(), new_aux

            (loss, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_aux, opt_state, loss

        return jax.jit(
            step,
            donate_argnums=(0, 1, 2),
            # aux/opt-state shardings None: they arrive replicated from
            # init_with_aux and jit keeps the layout.
            in_shardings=(param_sh, None, None, batch_sh, batch_sh),
            out_shardings=None,
        )

    def train_step_with_aux(
        self, params: Any, aux: Any, opt_state: Any, x: Any, y: Any
    ) -> tuple[Any, Any, Any, Any]:
        """One dp/FSDP step threading mutable collections: returns
        (params, aux, opt_state, loss). BatchNorm runs with
        ``train=True`` on the *logical* (whole) batch: under jit the
        sharded batch is one logical array, so XLA computes the global
        batch mean/var with cross-shard collectives — sync-BN semantics
        for free, and the updated stats stay replicated."""
        if self._step_aux_fn is None:
            self._step_aux_fn = self._build_step_aux(params)
        return self._step_aux_fn(params, aux, opt_state, x, y)
