"""FederationLearner — one protocol Node wrapping a whole on-chip
federation (the multi-slice / multi-host design, BASELINE config 5).

The reference deploys one process per FL node and gossips every model
over the network. On TPU pods the idiomatic layout is hierarchical
(SURVEY §7 "two planes"): *within* a host/slice, nodes are rows of a
:class:`~tpfl.parallel.federation.VmapFederation` — local training and
exact FedAvg are one XLA program, collectives ride ICI; *between* hosts,
each slice participates in the ordinary gossip protocol as ONE Node
(votes, heartbeats, model gossip over gRPC/DCN), contributing its
locally-aggregated model weighted by its total sample count.

A 2-host × 100-local-node deployment therefore runs the wire protocol
of a 2-node federation while training 200 logical nodes — DCN traffic
is O(hosts), not O(logical nodes).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpfl.learning.dataset.partition_strategies import RandomIIDPartitionStrategy
from tpfl.learning.dataset.tpfl_dataset import TpflDataset
from tpfl.learning.learner import Learner
from tpfl.learning.model import TpflModel
from tpfl.parallel.federation import VmapFederation
from tpfl.settings import Settings


class FederationLearner(Learner):
    """A Learner whose "local fit" is a whole vmapped sub-federation.

    Args:
        model: template TpflModel (architecture shared by all local
            nodes; its params seed the sub-federation each round).
        data: this host's dataset shard; partitioned across the local
            nodes on first fit.
        n_local_nodes: rows of the vmapped federation.
        local_rounds: sub-federation rounds per outer fit() call (each
            runs ``self.epochs`` local epochs).
        mesh: optional Mesh with a ``nodes`` axis for multi-chip hosts.
        partition_strategy: how to split ``data`` across local nodes.
    """

    def __init__(
        self,
        model: Optional[TpflModel] = None,
        data: Optional[TpflDataset] = None,
        addr: str = "unknown-node",
        aggregator: Optional[Any] = None,
        n_local_nodes: int = 8,
        local_rounds: int = 1,
        mesh: Optional[Any] = None,
        learning_rate: float = 0.1,
        batch_size: int = 32,
        partition_strategy: Any = RandomIIDPartitionStrategy,
        seed: int = 0,
    ) -> None:
        super().__init__(model, data, addr, aggregator)
        self.n_local_nodes = int(n_local_nodes)
        self.local_rounds = int(local_rounds)
        self.mesh = mesh
        self.learning_rate = float(learning_rate)
        self.batch_size = int(batch_size)
        self.partition_strategy = partition_strategy
        self.seed = int(seed)
        self._interrupt = threading.Event()
        # Elastic membership over the local node rows (ISSUE 17):
        # attached to the engine at fit(); churn between windows lands
        # as weight-mask edits via _window_weights. None = all rows
        # live (legacy behavior, no mask built).
        self.membership: Optional[Any] = None
        # Latest cadence checkpoint state (host numpy) — what the
        # SIGTERM handler publishes; never touches in-flight buffers.
        self._last_snapshot: "Optional[dict]" = None
        self._fed: Optional[VmapFederation] = None
        self._train_xs: Optional[Any] = None
        self._train_ys: Optional[Any] = None
        self._eval_xs: Optional[Any] = None
        self._eval_ys: Optional[Any] = None
        # Host-side (numpy) stacked train batches, cached so per-window
        # reshuffles don't re-partition the dataset (see _window_data).
        self._host_train: "Optional[tuple[np.ndarray, np.ndarray]]" = None

    # --- lazy setup ---

    def set_data(self, data: TpflDataset) -> None:
        super().set_data(data)
        self._train_xs = self._eval_xs = None
        self._host_train = None

    def set_membership(self, view: Any) -> None:
        """Attach a :class:`~tpfl.parallel.membership.MembershipView`
        over the local node rows. While attached, every window's fold
        weights come from the view (:meth:`_window_weights`) — joins,
        leaves, crashes and quarantine verdicts between windows are
        mask edits with zero recompiles; only a capacity-tier change
        restacks (handled at the next :meth:`fit`)."""
        self.membership = view

    def _window_weights(self, widx: int) -> "Optional[np.ndarray]":
        """Window ``widx``'s fold-weight vector from the attached
        membership view (None = unmasked legacy weighting). Called
        between windows by both drivers — the elastic re-mask seam."""
        del widx  # churn is wall-clock, not window-indexed
        if self.membership is None:
            return None
        return self.membership.weights()

    def _ensure_fed(self) -> VmapFederation:
        if self._fed is None:
            # No pinned mesh -> "auto": the engine spreads the local
            # node axis over the host's chips when SHARD_NODES is on
            # (a no-op on one device), so a multi-chip host's
            # sub-federation runs sharded without configuration.
            self._fed = VmapFederation(
                self.get_model().module,
                self.n_local_nodes,
                mesh=self.mesh if self.mesh is not None else "auto",
                learning_rate=self.learning_rate,
                seed=self.seed,
            )
        return self._fed

    def _host_stack(self, train: bool) -> tuple[np.ndarray, np.ndarray]:
        """Node-stacked [N, n_batches, b, ...] HOST arrays from this
        host's shard, equal batch counts (truncated to the smallest
        partition) — the pure-numpy half of the staging, reused by the
        per-window reshuffle."""
        parts = self.get_data().generate_partitions(
            self.n_local_nodes, self.partition_strategy, seed=self.seed
        )
        xs, ys = [], []
        for p in parts:
            batches = p.export(batch_size=self.batch_size, train=train)
            x, y = batches.stacked()
            xs.append(x)
            ys.append(y)
        n_batches = min(x.shape[0] for x in xs)
        if n_batches == 0:
            raise ValueError(
                f"Partitioning {self.get_data().num_samples(train)} samples "
                f"across {self.n_local_nodes} local nodes left an empty "
                f"batch set; lower batch_size or n_local_nodes"
            )
        return (
            np.stack([x[:n_batches] for x in xs]),
            np.stack([y[:n_batches] for y in ys]),
        )

    def _stack_split(self, train: bool) -> tuple[Any, Any]:
        """Host stack placed on the mesh (node axis sharded)."""
        return self._ensure_fed().shard_data(*self._host_stack(train))

    def _train_data(self) -> tuple[Any, Any]:
        if self._train_xs is None:
            if self._host_train is None:
                self._host_train = self._host_stack(train=True)
            self._train_xs, self._train_ys = self._ensure_fed().shard_data(
                *self._host_train
            )
        return self._train_xs, self._train_ys

    def _window_data(
        self, widx: int, start_round: int, n_rounds: int
    ) -> "Optional[tuple[Any, Any]]":
        """Window ``widx``'s mesh-placed batches: the cached host stack
        with a seeded per-window batch-order shuffle (window 0 keeps
        the export order — the legacy single-window fit byte-exact). A
        pure function of (seed, widx), so the sequential and pipelined
        drivers — and the inline vs prefetch-thread stagings — produce
        identical bytes. Runs on the prefetch thread under
        ``ENGINE_PREFETCH``; numpy + ``device_put`` only, no dispatch."""
        if widx == 0:
            return self._train_data()
        if self._host_train is None:
            self._host_train = self._host_stack(train=True)
        xs, ys = self._host_train
        order = np.random.default_rng(
            (self.seed * 1_000_003 + widx) & 0x7FFFFFFF
        ).permutation(xs.shape[1])
        return self._ensure_fed().shard_data(xs[:, order], ys[:, order])

    def _eval_data(self) -> tuple[Any, Any]:
        if self._eval_xs is None:
            self._eval_xs, self._eval_ys = self._stack_split(train=False)
        return self._eval_xs, self._eval_ys

    # --- Learner contract ---

    def _stack(self, tree: Any) -> Any:
        """Broadcast a single model's tree onto the local node axis."""
        return jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(
                p[None], (self.n_local_nodes, *jnp.shape(p))
            ),
            tree,
        )

    def fit(self) -> TpflModel:
        self._interrupt.clear()
        model = self.get_model()
        if self.membership is not None:
            cap = int(self.membership.capacity)
            if cap != self.n_local_nodes:
                # Capacity-tier boundary: restack the local federation
                # at the new tier — the ONE churn event that
                # re-partitions and re-lowers. Within a tier, fit()
                # re-masks only (zero recompiles).
                self.n_local_nodes = cap
                self._fed = None
                self._train_xs = self._eval_xs = None
                self._host_train = None
        fed = self._ensure_fed()
        if self.membership is not None:
            fed.engine.attach_membership(self.membership)
        xs, ys = self._train_data()

        params = self._stack(model.get_parameters())
        aux = self._stack(model.aux_state) if model.aux_state else None
        # Local rounds run in device-side windows of
        # SHARD_ROUNDS_PER_DISPATCH (engine fori_loop — one host
        # dispatch RTT per window instead of per round); interrupts are
        # honored between windows, which at the default window of 1 is
        # exactly the legacy per-round granularity. Each window trains
        # on _window_data's seeded batch order — the same pure function
        # of (seed, window index) on both drivers below, so
        # ENGINE_PREFETCH never changes bytes.
        window = max(1, int(Settings.SHARD_ROUNDS_PER_DISPATCH))
        # Preemption hardening (ISSUE 17): cadence snapshots every
        # CHECKPOINT_EVERY_WINDOWS windows into CHECKPOINT_DIR, and —
        # under CHECKPOINT_ON_SIGTERM, main thread only — a SIGTERM
        # handler that publishes the latest snapshot on the way out.
        ckpt = None
        snap_every = 0
        snapshot_to = None
        if Settings.CHECKPOINT_DIR and int(Settings.CHECKPOINT_EVERY_WINDOWS) > 0:
            from tpfl.management.checkpoint import EngineCheckpointer

            ckpt = EngineCheckpointer(Settings.CHECKPOINT_DIR, node=self._addr)
            snap_every = int(Settings.CHECKPOINT_EVERY_WINDOWS)

            def snapshot_to(rounds_at: int, state: dict) -> None:
                self._last_snapshot = state
                ckpt.save(state, step=int(rounds_at))

        sigterm_armed = False
        prev_sigterm: Any = None
        if (
            Settings.CHECKPOINT_ON_SIGTERM
            and Settings.CHECKPOINT_DIR
            and threading.current_thread() is threading.main_thread()
        ):
            from tpfl.management.checkpoint import (
                EngineCheckpointer,
                install_sigterm_checkpoint,
            )

            if ckpt is None:
                ckpt = EngineCheckpointer(
                    Settings.CHECKPOINT_DIR, node=self._addr
                )
            prev_sigterm = install_sigterm_checkpoint(
                ckpt, lambda: self._last_snapshot, node=self._addr
            )
            sigterm_armed = True
        try:
            if Settings.ENGINE_PREFETCH:
                # Free-running (Sebulba split): window N+1 is dispatched
                # before window N's host leg runs, and the next window's
                # batches are staged on the named prefetch thread — see
                # tpfl.parallel.window_pipeline.
                from tpfl.parallel.window_pipeline import WindowPipeline

                result, rounds_run = WindowPipeline(fed.engine).run(
                    params, xs, ys, epochs=self.epochs,
                    n_rounds=self.local_rounds, window=window, aux=aux,
                    data_for=self._window_data,
                    should_stop=self._interrupt.is_set,
                    weights_for=(
                        self._window_weights
                        if self.membership is not None
                        else None
                    ),
                    snapshot_every=snap_every,
                    snapshot_to=snapshot_to,
                    owner=self._addr,
                )
                if rounds_run and result is None:
                    # Interrupted shutdown (window_pipeline
                    # .interrupt_for): the in-flight window was
                    # abandoned, its donated buffers retired — no
                    # usable output, keep the pre-fit model.
                    return self.skip_fit(model)
                if rounds_run:
                    if aux is not None:
                        params, aux, _losses = result
                    else:
                        params, _losses = result
            else:
                rounds_run = 0
                widx = 0
                while rounds_run < self.local_rounds:
                    if self._interrupt.is_set():
                        break
                    k = min(window, self.local_rounds - rounds_run)
                    staged = self._window_data(widx, rounds_run, k)
                    if staged is not None:
                        xs, ys = staged
                    # The elastic re-mask seam (same as the pipeline's
                    # weights_for): churn since the last window lands
                    # as a weight edit, never a recompile.
                    w = self._window_weights(widx)
                    if aux is not None:
                        params, aux, _losses = fed.run_rounds(
                            params, xs, ys, weights=w, epochs=self.epochs,
                            aux=aux, n_rounds=k
                        )
                    else:
                        params, _losses = fed.run_rounds(
                            params, xs, ys, weights=w, epochs=self.epochs,
                            n_rounds=k
                        )
                    rounds_run += k
                    widx += 1
                    if snap_every and widx % snap_every == 0:
                        # Sequential driver: outputs are already
                        # materialized host-chainable arrays; snapshot
                        # inline at the cadence.
                        snapshot_to(
                            rounds_run,
                            fed.engine.export_state(params, aux=aux),
                        )
        finally:
            if sigterm_armed and prev_sigterm is not None:
                import signal

                signal.signal(signal.SIGTERM, prev_sigterm)
        if rounds_run == 0:
            return self.skip_fit(model)

        # After diffusion every row holds the slice aggregate: take row 0.
        agg = jax.tree_util.tree_map(lambda p: p[0], params)
        model.set_parameters(agg)
        if aux is not None:
            model.aux_state = jax.tree_util.tree_map(lambda a: a[0], aux)
        # Raw shard sample count — matching JaxLearner's convention
        # (jax_learner.py finish_fit), so mixed federations and slices
        # with different local_rounds/epochs weight fairly in FedAvg.
        model.set_contribution([self._addr], self.get_data().num_samples(True))
        self.add_callback_info_to_model(model)
        self._last_fit_model = model
        return model

    def skip_fit(self, model: Optional[TpflModel] = None) -> TpflModel:
        model = model if model is not None else self.get_model()
        model.set_contribution([self._addr], 0)
        self._last_fit_model = model
        return model

    def interrupt_fit(self) -> None:
        self._interrupt.set()

    def evaluate(self) -> dict[str, float]:
        model = self.get_model()
        fed = self._ensure_fed()
        xs, ys = self._eval_data()
        aux = self._stack(model.aux_state) if model.aux_state else None
        losses, accs = fed.evaluate(
            self._stack(model.get_parameters()), xs, ys, aux=aux
        )
        # host-sync: evaluation's consumption boundary — the metrics
        # are the product, fetched once per evaluate().
        loss_v = float(np.mean(np.asarray(losses)))
        acc_v = float(np.mean(np.asarray(accs)))  # host-sync: eval product
        return {"test_loss": loss_v, "test_metric": acc_v}
