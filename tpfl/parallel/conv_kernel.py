"""Per-node 3×3 convolution with a Pallas TPU backward — the hot op of
the vmapped federation round.

Why this exists: ``VmapFederation`` trains N nodes' DISTINCT conv
weights in one program. XLA lowers the vmapped conv FORWARD well
(grouped conv, measured ~27% MFU on the bench CNN), but its backward —
the weight gradient (a ``batch_group_count`` conv) and the input
gradient (a grouped transposed conv) — dominates the round at <11% MFU:
measured on one v5e chip, the 100-node CNN train step spends 2.95 ms in
the forward and ~19 ms in the backward. GEMM reformulations at the XLA
level (im2col / ``dot_general`` with a batch dim) are WORSE (58-89 ms):
XLA's batched-GEMM lowering cannot pipeline these shapes.

So: keep XLA's forward, replace only the backward with two Pallas
kernels that stream images through VMEM and feed the MXU with im2col
GEMMs built in-kernel (patches never touch HBM):

- ``dW = patches(x)^T @ dout`` — per (node, image-block) grid step the
  kernel zero-pads the image block in VMEM scratch, concatenates the
  kh·kw shifted slices into a ``[bb·H·W, kh·kw·Cin]`` patch matrix,
  and accumulates ``[kh·kw·Cin, Cout]`` partials in the revisited
  float32 output block (grid's minor dimension sweeps image blocks, so
  the accumulator lives in VMEM across the sweep).
- ``dx = patches(dout) @ rot180(w)^T`` — the transposed conv expressed
  the same way: halo-pad dout in scratch, im2col, one MXU GEMM per
  block, output written once.

The public entry is :class:`NodeConv`, a drop-in for ``nn.Conv`` with
the SAME param layout (kernel ``[kh, kw, Cin, Cout]``, bias
``[Cout]``) and the IDENTICAL forward (same ``lax.conv_general_dilated``
call — only gradient lowering changes). It vmaps: under ``jax.vmap``
the pallas grid gains the node dimension, which is exactly the
federation use. Reference seam being replaced: the per-process Ray
actor fits (``simulation/actor_pool.py:39-66``) — here the whole
N-node round is one XLA program and this kernel is its backward.

Restrictions (asserted): stride 1, SAME padding, odd square kernel —
what the zoo CNN uses. Interprets on CPU (tests), compiles on TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_DN = ("NHWC", "HWIO", "NHWC")


def _pick_bb(b: int, h: int, w: int, cin: int, cout: int, k: int,
             itemsize: int) -> int:
    """Images per grid step: bound the in-kernel patch matrix to ~2.5 MB
    of VMEM ([bb·h·w, k²·max(cin,cout)] at the input itemsize) and
    divide the batch."""
    budget = 2_500_000
    per_img = h * w * k * k * max(cin, cout) * itemsize
    bb = max(1, min(b, budget // max(per_img, 1)))
    while b % bb:
        bb -= 1
    return bb


def _build_patches(pad_ref, patch_ref, bb: int, h: int, w: int, k: int,
                   c: int):
    """Write the im2col matrix of the zero-haloed ``pad_ref`` into
    ``patch_ref`` ([bb, h, w, k²·c]); channel index is (di·k+dj)·c + ci.
    Stores (not concat): Mosaic relayouts the shifted slices on store,
    where a concat of offset-mismatched vectors fails to compile."""
    for di in range(k):
        for dj in range(k):
            idx = di * k + dj
            patch_ref[:, :, :, idx * c:(idx + 1) * c] = (
                pad_ref[:, di:di + h, dj:dj + w, :]
            )


def _dw_kernel(x_ref, g_ref, dw_ref, pad_ref, patch_ref, *, bb, h, w, k,
               cin, cout):
    bi = pl.program_id(0)
    r = k // 2

    @pl.when(bi == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    pad_ref[:] = jnp.zeros_like(pad_ref)
    pad_ref[:, r:r + h, r:r + w, :] = x_ref[:]
    _build_patches(pad_ref, patch_ref, bb, h, w, k, cin)
    pm = patch_ref[:].reshape(bb * h * w, k * k * cin)
    gm = g_ref[:].reshape(bb * h * w, cout)
    # MXU: contract the big M dim; accumulate f32 across image blocks.
    dw_ref[:] += lax.dot_general(
        pm, gm, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dx_kernel(g_ref, wrot_ref, dx_ref, pad_ref, patch_ref, *, bb, h, w,
               k, cin, cout):
    r = k // 2
    pad_ref[:] = jnp.zeros_like(pad_ref)
    pad_ref[:, r:r + h, r:r + w, :] = g_ref[:]
    _build_patches(pad_ref, patch_ref, bb, h, w, k, cout)
    pm = patch_ref[:].reshape(bb * h * w, k * k * cout)
    dx = lax.dot_general(
        pm, wrot_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dx_ref[:] = dx.reshape(bb, h, w, cin).astype(dx_ref.dtype)


def _conv_fwd_op(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=_DN
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def node_conv(x: jnp.ndarray, w: jnp.ndarray, interpret: Optional[bool] = None):
    """3×3/SAME/stride-1 conv [B,H,W,Cin]·[k,k,Cin,Cout] -> [B,H,W,Cout]
    with XLA forward and Pallas backward. Vmappable over a leading node
    axis on both operands."""
    return _conv_fwd_op(x, w)


def _nc_fwd(x, w, interpret):
    return _conv_fwd_op(x, w), (x, w)


def _nc_bwd(interpret, res, g):
    x, w = res
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, w_, cin = x.shape
    k, k2, _, cout = w.shape
    assert k == k2 and k % 2 == 1, "NodeConv: odd square kernels only"
    g = g.astype(x.dtype)
    bb = _pick_bb(b, h, w_, cin, cout, k, jnp.dtype(x.dtype).itemsize)
    grid = (b // bb,)
    halo = k - 1

    dw = pl.pallas_call(
        functools.partial(
            _dw_kernel, bb=bb, h=h, w=w_, k=k, cin=cin, cout=cout
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, h, w_, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bb, h, w_, cout), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (k * k * cin, cout), lambda i: (0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((k * k * cin, cout), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bb, h + halo, w_ + halo, cin), x.dtype),
            pltpu.VMEM((bb, h, w_, k * k * cin), x.dtype),
        ],
        interpret=interpret,
    )(x, g)
    # [k²·cin, cout] with channel index (di·k+dj)·cin + ci -> flax HWIO.
    dw = dw.reshape(k, k, cin, cout).astype(w.dtype)

    # dx = conv_T(g, w): patches(g) @ rot180(w)^T, built as a [k²·cout,
    # cin] matrix whose row index matches _patches' channel order.
    wrot = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2).reshape(
        k * k * cout, cin
    )
    dx = pl.pallas_call(
        functools.partial(
            _dx_kernel, bb=bb, h=h, w=w_, k=k, cin=cin, cout=cout
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, h, w_, cout), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((k * k * cout, cin), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bb, h, w_, cin), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w_, cin), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((bb, h + halo, w_ + halo, cout), g.dtype),
            pltpu.VMEM((bb, h, w_, k * k * cout), g.dtype),
        ],
        interpret=interpret,
    )(g, wrot)
    return dx, dw


node_conv.defvjp(_nc_fwd, _nc_bwd)


@jax.custom_vjp
def conv_fwd_style(x: jnp.ndarray, w: jnp.ndarray):
    """Same conv as :func:`node_conv`, but with BOTH backward passes
    expressed as ordinary FORWARD convolutions at the XLA level:

    - ``dx = conv_SAME(dout, rot180(w) io-swapped)`` — the standard
      transposed-conv identity for stride 1 / SAME / odd kernels;
    - ``dW = conv(x, dout)`` with dimension numbers ``CHWN/IHWO/HWNC``
      (Cin as the conv batch, the real batch as the contraction
      feature, dout as a big-window kernel).

    Why: JAX's built-in conv transpose rules emit
    ``batch_group_count``/grouped-transpose convolutions that, once
    vmapped over a nodes axis, lower ~6x slower than forward-style
    grouped convs on TPU (measured on the bench CNN: 22.0 -> 21.1 ms
    per 100-node step, and the dW/dx ops individually 4.5-6.6 ms ->
    forward-conv class). Gradients are numerically IDENTICAL to the
    autodiff path (same conv op, exact — tested).

    Restrictions: stride 1, SAME padding, odd square kernel."""
    return _conv_fwd_op(x, w)


def _fs_fwd(x, w):
    return _conv_fwd_op(x, w), (x, w)


def _fs_bwd(res, g):
    x, w = res
    g = g.astype(x.dtype)
    k = w.shape[0]
    assert k == w.shape[1] and k % 2 == 1, "conv_fwd_style: odd square only"
    r = k // 2
    w_flip = jnp.flip(w, (0, 1)).swapaxes(2, 3)  # [k, k, Cout, Cin]
    dx = lax.conv_general_dilated(
        g, w_flip, (1, 1), "SAME", dimension_numbers=_DN
    )
    dw = lax.conv_general_dilated(
        x, g, (1, 1), [(r, r), (r, r)],
        dimension_numbers=("CHWN", "IHWO", "HWNC"),
    ).astype(w.dtype)
    return dx, dw


conv_fwd_style.defvjp(_fs_fwd, _fs_bwd)
