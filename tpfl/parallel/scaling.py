"""Static scaling analysis — prove the sharding story from compiled HLO.

On a single-core virtual device mesh, wall-clock scaling tables are
meaningless (every "device" shares one core), so claims like "the
federated reduction scales over ICI" must be proven STATICALLY: lower
the program at several mesh widths, read the compiled HLO, and assert

- per-device FLOPs fall ~1/d (the compute is actually partitioned);
- the bytes moved by cross-device collectives are O(model parameters)
  and INDEPENDENT of the node count / batch size (one all-reduce of
  the aggregate, not a gather of per-node replicas).

Used by tests/test_scaling_model.py and by ``__graft_entry__``'s
multichip dryrun, whose MULTICHIP report carries the verdict.
"""

from __future__ import annotations

import re
from typing import Any

from tpfl.management.profiling import cost_model

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
}

_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([0-9,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def collective_bytes(hlo_text: str) -> dict[str, int]:
    """Bytes produced by each collective kind in optimized HLO text
    (result shapes of ``all-reduce``/``all-gather``/… ops; ``-start``
    variants counted once, ``-done`` skipped)."""
    out: dict[str, int] = {}
    for line in hlo_text.splitlines():
        for kind in _COLLECTIVES:
            token = f" {kind}("
            start_token = f" {kind}-start("
            if token not in line and start_token not in line:
                continue
            lhs = line.split(f"{kind}-start(")[0].split(f"{kind}(")[0]
            # result may be a tuple: every shape before the op name
            total = sum(
                _shape_bytes(m.group(1), m.group(2))
                for m in _SHAPE_RE.finditer(lhs)
            )
            out[kind] = out.get(kind, 0) + total
            break
    return out


def analyze_compiled(compiled: Any) -> dict[str, Any]:
    """{"flops": per-device flops, "collectives": {kind: bytes},
    "collective_bytes": total}.

    FLOPs come from the shared :class:`~tpfl.management.profiling
    .CostModel` — the ONE ``cost_analysis()`` call path (bench.py's
    live MFU uses the same one, with the same scan-counted-once
    caveat), so static scaling analysis and live MFU can never
    disagree about what a program costs."""
    coll = collective_bytes(compiled.as_text())
    return {
        "flops": cost_model.xla_flops(compiled) or 0.0,
        "collectives": coll,
        "collective_bytes": sum(coll.values()),
    }


def params_bytes(tree: Any) -> int:
    import jax

    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def check_scaling(
    records: list[dict],
    params_nbytes: int,
    flops_tol: float = 0.25,
    collective_factor: float = 4.0,
) -> list[str]:
    """Assert the scaling conditions over per-width analysis records
    ``[{"width": d, "flops": F_d, "collective_bytes": C_d}, ...]``.
    Returns a list of human-readable failures (empty = pass).

    - F_d · d within ``flops_tol`` of F_1 (per-device compute ∝ 1/d;
      the slack absorbs padding and the O(params) aggregation ops);
    - for d > 1: C_d ≤ collective_factor · params_nbytes (the
      reduction moves O(params), never O(params · nodes)), and C_d is
      width-independent within 2× (no hidden re-replication).
    """
    failures: list[str] = []
    base = next((r for r in records if r["width"] == 1), records[0])
    # Compare total WORK (per-device flops x width) so the check is
    # meaningful even when no width-1 record exists.
    base_work = base["flops"] * base["width"]
    for r in records:
        work = r["flops"] * r["width"]
        if not (
            base_work * (1 - flops_tol) <= work <= base_work * (1 + flops_tol)
        ):
            failures.append(
                f"width {r['width']}: per-device flops x width = {work:.0f} "
                f"not within {flops_tol:.0%} of base work "
                f"{base_work:.0f} — compute is not 1/d-partitioned"
            )
    multi = [r for r in records if r["width"] > 1]
    for r in multi:
        if r["collective_bytes"] > collective_factor * params_nbytes:
            failures.append(
                f"width {r['width']}: collective bytes "
                f"{r['collective_bytes']} exceed {collective_factor}x "
                f"params ({params_nbytes} B) — reduction is not O(params)"
            )
    if multi:
        cs = [r["collective_bytes"] for r in multi]
        if max(cs) > 2 * max(1, min(cs)):
            failures.append(
                f"collective bytes vary {min(cs)}..{max(cs)} across widths "
                f"— hidden width-dependent re-replication"
            )
    return failures
