"""TPU execution layer: meshes, vmapped federations, sharded training.

This is the green-field value-add over the reference (SURVEY §2.10): the
reference's only intra-host parallelism is a Ray actor pool multiplexing
N learner *processes* over K CPUs (``actor_pool.py:69``), with weights
round-tripping through pickle on every hop. Here:

- :class:`FederationEngine` — the pod-scale seam (tpfl.parallel.engine):
  an ENTIRE federation round (per-node local train, gossip exchange,
  streaming FedAvg/SCAFFOLD/FedProx fold) compiled to one sharded XLA
  program over the mesh, gossip realized as ``lax.psum`` collectives on
  the ``nodes`` axis, node counts padded to device multiples with
  zero-weight rows, and multi-round ``lax.fori_loop`` windows that pay
  the host dispatch RTT once per window (docs/scaling.md).
- :class:`VmapFederation` — the stable high-level API over the engine:
  N homogeneous FL nodes stacked on a leading node axis; every node's
  local epoch runs inside ONE compiled XLA program (vmap over
  lax.scan), the node axis is sharded over the device mesh, and FedAvg
  is an exact on-device weighted reduction instead of
  gossip-until-converged.
- :func:`create_mesh` / :func:`federation_sharding` — mesh + sharding
  helpers for single-host (8-chip) and multi-host topologies; 2D
  ``nodes x model`` meshes shard each node's model over chips per a
  :class:`SpecLayout` per-leaf PartitionSpec policy
  (``SHARD_MODEL``/``SHARD_LAYOUT``), federating models bigger than
  one chip's HBM (docs/scaling.md "2D mesh").
- :class:`ShardedTrainer` — data-parallel + FSDP sharding for one large
  model across the mesh (tpfl.parallel.sharded).
"""

from tpfl.parallel.mesh import (
    HOST_AXIS,
    MODEL_AXIS,
    NODE_AXIS,
    SpecLayout,
    create_mesh,
    federation_sharding,
    global_model_shardings,
    layout_for_module,
    pad_node_axis,
    pad_node_weights,
    padded_node_count,
    replicated,
    shard_stacked,
    stacked_model_shardings,
    transformer_layout,
)
from tpfl.parallel.engine import (
    EngineWindow,
    FederationEngine,
    FedBuffSchedule,
    resolve_shard_hosts,
    sample_participants,
)
from tpfl.parallel.distributed import (
    ensure_distributed,
    global_put,
    is_multiprocess,
    local_data,
)
from tpfl.parallel.population import ClientPopulation
from tpfl.parallel.federation import VmapFederation
from tpfl.parallel.federation_learner import FederationLearner
from tpfl.parallel.window_pipeline import WindowPipeline, WindowPrefetcher
from tpfl.parallel.moe import make_moe_layer, moe_dispatch
from tpfl.parallel.pipeline import make_pipeline, pipeline_forward
from tpfl.parallel.ring_attention import (
    blockwise_attention,
    make_ring_attention,
    ring_attention,
)
from tpfl.parallel.sharded import ShardedTrainer


def __getattr__(name):
    # Lazy: flash_attention pulls jax.experimental.pallas (~1s import),
    # a serving-only fast path most tpfl.parallel users never touch.
    if name == "flash_attention":
        from tpfl.parallel.flash_kernel import flash_attention

        return flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))

__all__ = [
    "create_mesh",
    "federation_sharding",
    "replicated",
    "padded_node_count",
    "pad_node_axis",
    "pad_node_weights",
    "shard_stacked",
    "HOST_AXIS",
    "MODEL_AXIS",
    "NODE_AXIS",
    "SpecLayout",
    "layout_for_module",
    "transformer_layout",
    "stacked_model_shardings",
    "global_model_shardings",
    "FederationEngine",
    "EngineWindow",
    "FedBuffSchedule",
    "ClientPopulation",
    "ensure_distributed",
    "is_multiprocess",
    "global_put",
    "local_data",
    "resolve_shard_hosts",
    "WindowPipeline",
    "WindowPrefetcher",
    "sample_participants",
    "VmapFederation",
    "FederationLearner",
    "ShardedTrainer",
    "flash_attention",
    "blockwise_attention",
    "ring_attention",
    "make_ring_attention",
    "make_pipeline",
    "make_moe_layer",
    "moe_dispatch",
    "pipeline_forward",
]
