"""Opt-in process isolation for fallback fits.

The reference's Ray actor pool runs every learner in its own process,
so a crashing learner (or a native-library segfault) kills one actor,
which the pool flags and respawns (``actor_pool.py:203-357``). tpfl's
batched-vmap pool is threads in one process — faster (no object-store
round trips), but a hard crash would take all nodes down. With
``Settings.SIM_PROCESS_ISOLATION = True`` the pool's FALLBACK path
(jobs that can't batch) runs each fit in a spawned worker process
instead. Workers share one pool, so a crash breaks the WHOLE pool for
every in-flight job; ``isolated_fit`` rebuilds the pool and retries
each affected job once (serialized), so a dead worker ends up failing
only the job that crashed it while concurrent innocents complete on the
rebuilt pool — the reference's isolation property restored (modulo two
unrelated crashes hitting the same job's both attempts).

Scope: plain ``JaxLearner`` fits (no aggregator callbacks — SCAFFOLD /
FedProx state lives in-process; such jobs stay on the thread pool, with
a log line). The child rebuilds a real JaxLearner from shipped arrays,
so the fit math — including per-(seed, addr, round) shuffle seeding —
is identical to the in-process path (tested).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Optional

import numpy as np

from tpfl.management.logger import logger
from tpfl.settings import Settings

_executor = None
_executor_lock = threading.Lock()
# Serializes bystander retries after a pool break: a crashing job's
# retry can then only break a pool while it alone holds the lock, so
# every other retrying job gets a fresh executor after it.
_retry_lock = threading.Lock()


def _child_init() -> None:
    """Worker initializer (runs before jax import in the child): pin
    isolated fits to the host CPU. The TPU belongs to the parent's
    batched-vmap path; a fleet of worker processes grabbing the chip
    would contend with it, and CPU f32 keeps isolated results exactly
    reproducible against a CPU parent (the parity test)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    # Images that register a TPU plugin at interpreter start ignore the
    # env var; only a config update before backend init sticks.
    import jax

    jax.config.update("jax_platforms", "cpu")


def _get_executor():
    """Lazy spawn-context ProcessPoolExecutor; rebuilt after a crash."""
    global _executor
    with _executor_lock:
        if _executor is None:
            import multiprocessing as mp
            from concurrent.futures import ProcessPoolExecutor

            workers = int(Settings.SIM_WORKERS) or 4
            _executor = ProcessPoolExecutor(
                max_workers=workers,
                mp_context=mp.get_context("spawn"),
                initializer=_child_init,
            )
        return _executor


def _discard_executor(only: Any = None) -> None:
    """Tear down the current executor. With ``only``, discard it ONLY
    if it still IS the current one — a late-arriving failure handler
    for a pool that was already replaced must not shut down the fresh
    pool other jobs are retrying on (their pending futures would be
    cancelled, and CancelledError is not BrokenProcessPool)."""
    global _executor
    with _executor_lock:
        if only is not None and _executor is not only:
            ex = None
        else:
            ex, _executor = _executor, None
    if ex is not None:
        ex.shutdown(wait=False, cancel_futures=True)


def shutdown() -> None:
    """Tear down the worker pool (tests / reconfiguration)."""
    _discard_executor()


def _child_fit(payload: bytes) -> bytes:
    """Worker-process entry: rebuild a JaxLearner and run the REAL fit
    (same seeding, same compiled program shape as the inline path).

    Top-level function (spawn pickles it by reference). Returns encoded
    params via tpfl serialization — never pickle of arbitrary objects
    back into the parent. The fresh process has default Settings, so
    the result is encoded exact (no WIRE_DTYPE downcast)."""
    job = pickle.loads(payload)
    if job.get("_test_crash"):  # test hook: simulate a native crash
        import os

        os._exit(42)

    from tpfl.learning.dataset.export import Batches
    from tpfl.learning.dataset.tpfl_dataset import TpflDataset
    from tpfl.learning.jax_learner import JaxLearner
    from tpfl.learning.model import TpflModel

    module = pickle.loads(job["module"])
    model = TpflModel(module=module)
    model.set_parameters(job["params"])
    x, y = job["x"], job["y"]
    data = TpflDataset.from_arrays(x, y, x[:1], y[:1])
    learner = JaxLearner(
        model,
        data,
        addr=job["addr"],
        learning_rate=job["learning_rate"],
        batch_size=job["batch_size"],
    )
    # Inject the parent's exported batches verbatim (same export seed,
    # same round counter): the per-epoch shuffles reproduce exactly.
    learner._train_batches = Batches(
        x, y, job["batch_size"], seed=job["export_seed"]
    )
    learner._round_counter = job["round_counter"]
    learner.set_epochs(job["epochs"])
    fitted = learner.fit()
    # Dense on purpose: this is a same-host process round-trip, not the
    # gossip wire — a lossy WIRE_CODEC must not perturb the fit result.
    return fitted.encode_parameters(codec="dense")


def extract_job(learner: Any) -> Optional[bytes]:
    """Serialize a JaxLearner fit into a child-process payload, or None
    when the job is outside the isolation scope: aggregator callbacks
    (their state lives in-process), mutable collections, custom
    optimizer/loss, or an un-picklable module."""
    from tpfl.learning.jax_learner import (
        JaxLearner,
        _addr_seed,
        cross_entropy_loss,
        default_optimizer,
    )

    if not isinstance(learner, JaxLearner):
        return None
    if learner.callbacks:
        return None
    if learner._optimizer_factory is not default_optimizer:
        return None
    if learner._loss_fn is not cross_entropy_loss:
        return None
    model = learner.get_model()
    if model.aux_state:
        return None  # BatchNorm stats threading stays in-process
    try:
        module_bytes = pickle.dumps(model.module)
        # Dense: in-process hand-off to the child, not wire traffic.
        params = model.encode_parameters(codec="dense")
    except Exception:
        return None
    export_seed = (Settings.SEED or 0) + _addr_seed(learner.get_addr())
    batches = learner._train_data(export_seed)
    job = {
        "module": module_bytes,
        "params": params,
        "x": np.asarray(batches.x),
        "y": np.asarray(batches.y),
        "export_seed": batches.seed,
        "addr": learner.get_addr(),
        "learning_rate": learner.learning_rate,
        "batch_size": learner.batch_size,
        "epochs": learner.epochs,
        "round_counter": learner._round_counter,
    }
    return pickle.dumps(job)


def isolated_fit(learner: Any, payload: Optional[bytes] = None) -> Any:
    """Run one fit in a worker process; apply the result to the
    learner.

    Workers share one ProcessPoolExecutor, and CPython marks the WHOLE
    pool broken when any worker dies — so a crash surfaces
    BrokenProcessPool to every in-flight job, innocents included.
    Containment therefore takes two steps: rebuild the pool, then retry
    the job once (retries serialized, so a crashing job's retry breaks
    only a pool it holds exclusively). The job whose payload actually
    crashes the worker fails both attempts and raises; a concurrent
    innocent fails only if a second, unrelated crash also lands on its
    retry."""
    from concurrent.futures.process import BrokenProcessPool

    if payload is None:
        payload = extract_job(learner)
    if payload is None:
        raise ValueError("learner is outside the isolation scope")
    ex = _get_executor()
    try:
        result = ex.submit(_child_fit, payload).result()
    except BrokenProcessPool:
        _discard_executor(only=ex)  # replace the broken pool, not a successor
        with _retry_lock:
            ex2 = _get_executor()
            try:
                result = ex2.submit(_child_fit, payload).result()
            except BrokenProcessPool as e:
                _discard_executor(only=ex2)
                raise RuntimeError(
                    f"isolated fit worker died (both attempts): {e}"
                ) from e
    model = learner.get_model()
    # build_copy(params=bytes) restores the child's contributors and
    # num_samples from the payload itself.
    fitted = model.build_copy(params=result)
    learner.set_model(fitted)
    learner._round_counter += 1
    learner._last_fit_model = fitted
    logger.debug(learner.get_addr(), "isolated fit complete")
    return fitted
