"""Scale-out simulation layer.

The reference multiplexes N logical FL nodes over K Ray actor processes
(``simulation/actor_pool.py:69``, ``virtual_learner.py:31``, activation
hook ``simulation/__init__.py:16-33``) — each ``fit()`` ships the whole
learner through the Ray object store to a worker.

The TPU-native replacement keeps every learner in-process and instead
**batches concurrent ``fit()`` calls into one vmapped XLA program**: when
several protocol nodes (the round's train set) hit ``fit()`` within the
batching window, their parameters/corrections/data are stacked on a
leading ``nodes`` axis and trained by a single compiled program — N
local trainings for the price of one XLA dispatch (chunked to bound
memory). Heterogeneous or non-JAX jobs fall back to a thread pool.

Activation mirrors the reference hook: :func:`try_init_learner_with_simulation`
wraps a learner in :class:`VirtualNodeLearner` unless
``Settings.DISABLE_SIMULATION``.
"""

from tpfl.simulation.pool import SuperLearnerPool
from tpfl.simulation.virtual_learner import (
    VirtualNodeLearner,
    try_init_learner_with_simulation,
)

__all__ = [
    "SuperLearnerPool",
    "VirtualNodeLearner",
    "try_init_learner_with_simulation",
]
