"""SuperLearnerPool — the shared fit-batching executor.

Reference seam: ``SuperActorPool`` (``simulation/actor_pool.py:69-99``),
a singleton Ray actor pool all ``VirtualNodeLearner``s submit to. Here
the pool is a dispatcher thread that collects concurrent fit
submissions for a short window (``Settings.SIM_BATCH_WINDOW``), groups
them by homogeneity signature, and runs each group as ONE vmapped XLA
program (``batched_fit``). Jobs that cannot batch (unique signature,
non-JaxLearner, or a batched-path failure) run on a thread pool of
``Settings.SIM_WORKERS`` threads instead — the reference's K-worker
multiplexing without the object-store round-trips.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from tpfl.learning.jax_learner import JaxLearner
from tpfl.learning.learner import Learner
from tpfl.learning.model import TpflModel
from tpfl.management.logger import logger
from tpfl.settings import Settings
from tpfl.simulation.batched_fit import job_signature, run_batched_fits


class _FitJob:
    __slots__ = ("learner", "done", "error", "group_hint")

    def __init__(self, learner: Learner, group_hint: int = 0) -> None:
        self.learner = learner
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.group_hint = group_hint


class SuperLearnerPool:
    """Process-wide singleton batching executor (reference
    ``SuperActorPool`` singleton semantics, ``actor_pool.py:77-99``)."""

    _instance: Optional["SuperLearnerPool"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._queue: list[_FitJob] = []
        self._queue_lock = threading.Condition()
        self._dispatcher: Optional[threading.Thread] = None
        self._stop = False
        workers = int(Settings.SIM_WORKERS) or (os.cpu_count() or 4)
        self._fallback = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="tpfl-sim"
        )

    @classmethod
    def instance(cls) -> "SuperLearnerPool":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = SuperLearnerPool()
            return cls._instance

    @classmethod
    def reset(cls, clear_compiled: bool = True) -> None:
        """Tear down the singleton (tests / reconfiguration).

        ``clear_compiled``: also drop the process-lifetime compiled
        program caches (default — a reset between experiments must not
        accrete programs forever). Pass False to keep them when the
        next experiment reuses the same architectures (e.g. the test
        suite's per-test pool isolation)."""
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            with inst._queue_lock:
                inst._stop = True
                inst._queue_lock.notify_all()
            if inst._dispatcher is not None:
                inst._dispatcher.join(timeout=5)
            inst._fallback.shutdown(wait=False)
        # Drop process-lifetime compiled-program caches with the pool:
        # a host cycling many architectures/experiments must not
        # accrete compiled programs forever (VERDICT r3 weak #5).
        if clear_compiled:
            from tpfl.learning.jax_learner import clear_compiled_caches

            clear_compiled_caches()

    # --- submission (called from each node's learning thread) ---

    def submit_fit(self, learner: Learner, group_hint: int = 0) -> TpflModel:
        """Block until the pool has trained this learner; returns its
        updated model (mirrors ``VirtualNodeLearner.fit`` blocking on the
        actor result, reference ``virtual_learner.py:101-137``).

        ``group_hint``: expected number of concurrent fits (the round's
        train-set size) — the dispatcher holds the batch until that many
        arrive or ``SIM_BATCH_MAX_WAIT`` elapses."""
        job = _FitJob(learner, group_hint=group_hint)
        # Submission == fit entry: drop any stale interrupt from a past
        # experiment (inline fit() clears on entry; the batched path
        # honors interrupts set after this point).
        reset = getattr(learner, "reset_interrupt", None)
        if reset is not None:
            reset()
        with self._queue_lock:
            if self._stop:
                raise RuntimeError("SuperLearnerPool is shut down")
            self._queue.append(job)
            if self._dispatcher is None or not self._dispatcher.is_alive():
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop,
                    name="tpfl-sim-dispatcher",
                    daemon=True,
                )
                self._dispatcher.start()
            self._queue_lock.notify_all()
        job.done.wait()
        if job.error is not None:
            raise job.error
        # The model finish_fit produced — NOT learner.get_model(), which
        # a concurrent FullModelCommand (lapped trainer) may have rebound
        # to the round's aggregate.
        fitted = learner._last_fit_model
        return fitted if fitted is not None else learner.get_model()

    # --- dispatcher ---

    def _dispatch_loop(self) -> None:
        while True:
            with self._queue_lock:
                while not self._queue and not self._stop:
                    self._queue_lock.wait(timeout=1.0)
                if self._stop:
                    for j in self._queue:
                        j.error = RuntimeError("pool shut down")
                        j.done.set()
                    self._queue.clear()
                    return
            # Batching window: let the rest of the train set arrive.
            # When submitters hint the group size (train-set size), hold
            # up to SIM_BATCH_MAX_WAIT until the group is full — capped
            # by the number of in-process nodes, so a 1-node real-network
            # process never waits for peers that live elsewhere.
            from tpfl.simulation.virtual_learner import VirtualNodeLearner

            deadline = time.monotonic() + float(Settings.SIM_BATCH_MAX_WAIT)
            window_end = time.monotonic() + float(Settings.SIM_BATCH_WINDOW)
            while True:
                with self._queue_lock:
                    jobs = list(self._queue)
                hints = [j.group_hint for j in jobs if j.group_hint > 0]
                target = (
                    min(max(hints), max(VirtualNodeLearner.live_count(), 1))
                    if hints
                    else 0
                )
                now = time.monotonic()
                if hints and (len(jobs) >= target or now >= deadline):
                    break
                if not hints and now >= window_end:
                    break
                time.sleep(0.02)
            with self._queue_lock:
                batch, self._queue = self._queue, []
            try:
                self._run_batch(batch)
            except BaseException as e:  # dispatcher must survive anything
                for j in batch:
                    if not j.done.is_set():
                        j.error = e
                        j.done.set()

    def _run_batch(self, batch: list[_FitJob]) -> None:
        groups: dict[Any, list[_FitJob]] = {}
        singles: list[_FitJob] = []
        for job in batch:
            if isinstance(job.learner, JaxLearner):
                try:
                    groups.setdefault(job_signature(job.learner), []).append(job)
                    continue
                except Exception:
                    pass
            singles.append(job)

        for sig, jobs in groups.items():
            if len(jobs) == 1:
                singles.append(jobs[0])
                continue
            try:
                failed = run_batched_fits(sig, [j.learner for j in jobs])
            except Exception as e:
                # Signature-level failure (nothing trained): everyone
                # falls back. Chunk-level failures are reported via
                # ``failed`` instead — re-fitting a chunk that already
                # trained would double its epochs and callback deltas.
                logger.info(
                    "simulation",
                    f"Batched fit of {len(jobs)} nodes failed ({e}); "
                    "falling back to per-learner fits",
                )
                singles.extend(jobs)
                continue
            failed_ids = {id(ln) for ln in failed}
            for j in jobs:
                if id(j.learner) in failed_ids:
                    singles.append(j)
                else:
                    j.done.set()

        def run_single(learner):
            if Settings.SIM_PROCESS_ISOLATION:
                from tpfl.simulation import isolated

                payload = isolated.extract_job(learner)
                if payload is not None:
                    return isolated.isolated_fit(learner, payload)
                logger.debug(
                    "simulation",
                    "fit outside isolation scope; running in-process",
                )
            return learner.fit()

        futures = [
            (j, self._fallback.submit(run_single, j.learner)) for j in singles
        ]
        for j, fut in futures:
            try:
                fut.result()
            except BaseException as e:
                j.error = e
            j.done.set()
