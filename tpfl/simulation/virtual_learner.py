"""VirtualNodeLearner — learner decorator routing fits to the pool.

Parity with reference ``simulation/virtual_learner.py:31-141``: wraps
any :class:`Learner`, delegates everything, but ``fit()`` goes through
the shared :class:`SuperLearnerPool` so concurrent fits across protocol
nodes batch into one vmapped XLA program. Unlike the reference,
``interrupt_fit`` IS implemented (delegates to the inner learner):
an interrupt delivered before the batch dispatches skips that node's
training entirely (zero contribution); once the compiled batched round
launches it is not interruptible — only the inline fallback can still
stop between epochs.

Activation hook parity: ``try_init_learner_with_simulation`` mirrors
``try_init_learner_with_ray`` (``simulation/__init__.py:16-33``) — wraps
unless ``Settings.DISABLE_SIMULATION``.
"""

from __future__ import annotations

import weakref
from typing import Optional, Union

from tpfl.learning.dataset.tpfl_dataset import TpflDataset
from tpfl.learning.learner import Learner
from tpfl.learning.model import TpflModel
from tpfl.settings import Settings
from tpfl.simulation.pool import SuperLearnerPool

_live_learners: "weakref.WeakSet[VirtualNodeLearner]" = weakref.WeakSet()


class VirtualNodeLearner(Learner):
    """Decorator: same Learner surface, pooled execution."""

    def __init__(self, learner: Learner) -> None:
        # No super().__init__: all state lives in the wrapped learner.
        self.learner = learner
        self._group_hint: "int | list[str]" = 0
        self._last_fit_model = None  # Learner contract (pool fit seam)
        _live_learners.add(self)

    @staticmethod
    def live_count() -> int:
        """Upper bound on in-process simulated nodes — caps how long the
        pool waits for a hinted fit group to fill (a 1-node real-network
        process must not wait for 7 peers that live elsewhere)."""
        return len(_live_learners)

    # --- pooled execution ---

    def set_fit_group_hint(self, peers: "int | list[str]") -> None:
        self._group_hint = peers

    def fit(self) -> TpflModel:
        hint = self._group_hint
        if not isinstance(hint, int):
            # Exact local group size: only the train-set members hosted
            # in THIS process will submit fits here — waiting for the
            # remote ones would stall every round by SIM_BATCH_MAX_WAIT.
            local = {ln.get_addr() for ln in _live_learners}
            hint = len(set(hint) & local)
        return SuperLearnerPool.instance().submit_fit(
            self.learner, group_hint=hint
        )

    def interrupt_fit(self) -> None:
        self.learner.interrupt_fit()

    def evaluate(self) -> dict[str, float]:
        return self.learner.evaluate()

    # --- pure delegation ---

    @property
    def callbacks(self):  # type: ignore[override]
        return self.learner.callbacks

    @property
    def epochs(self) -> int:  # type: ignore[override]
        return self.learner.epochs

    def set_addr(self, addr: str) -> None:
        self.learner.set_addr(addr)

    def get_addr(self) -> str:
        return self.learner.get_addr()

    def set_model(self, model: Union[TpflModel, list, bytes]) -> None:
        self.learner.set_model(model)

    def get_model(self) -> TpflModel:
        return self.learner.get_model()

    def set_data(self, data: TpflDataset) -> None:
        self.learner.set_data(data)

    def get_data(self) -> TpflDataset:
        return self.learner.get_data()

    def set_epochs(self, epochs: int) -> None:
        self.learner.set_epochs(epochs)

    def update_callbacks_with_model_info(self) -> None:
        self.learner.update_callbacks_with_model_info()

    def add_callback_info_to_model(self, model: Optional[TpflModel] = None) -> None:
        self.learner.add_callback_info_to_model(model)

    def get_framework(self) -> str:
        return self.learner.get_framework()

    def get_num_samples(self) -> int:
        return self.learner.get_num_samples()


def try_init_learner_with_simulation(learner: Learner) -> Learner:
    """Wrap ``learner`` for pooled simulation unless disabled (reference
    activation hook ``simulation/__init__.py:16-33``)."""
    if Settings.DISABLE_SIMULATION or isinstance(learner, VirtualNodeLearner):
        return learner
    return VirtualNodeLearner(learner)
