"""Batched local training — many learners' fits as one XLA program.

This is the bridge between the protocol world (N independent ``Node``
objects, each with a :class:`JaxLearner`) and the vectorized TPU
execution layer (``tpfl.parallel.VmapFederation``): a group of
homogeneous fit jobs is stacked on a leading ``nodes`` axis and trained
by ONE jitted ``vmap(local_fit)`` call. Replaces the reference's
per-learner Ray actor dispatch (``actor_pool.py:39-66``) where each fit
is a separate process round-trip.

Semantics vs ``JaxLearner.fit``: identical optimizer/loss/correction
handling and callback lifecycle; the one divergence is that the batch
order is shuffled once per round (not per epoch) because all epochs run
inside the compiled program. Nodes with fewer batches than the group
max are padded with masked no-op batches, so partitions of unequal size
batch together exactly.

The compiled program itself is built by the federation engine
(``tpfl.parallel.engine.build_batched_fit_program`` — the one seam the
vmapped federation, this pool, and the bench all ride), and when
``Settings.SHARD_NODES`` is on with a multi-chip host the stacked node
axis is placed over the ``nodes`` mesh
(``engine.maybe_nodes_mesh``), so pool fits run SPMD across chips.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpfl.learning.jax_learner import JaxLearner
from tpfl.management import ledger, profiling
from tpfl.management.logger import logger
from tpfl.parallel.engine import build_batched_fit_program, maybe_nodes_mesh
from tpfl.parallel.mesh import federation_sharding
from tpfl.settings import Settings


def job_signature(learner: JaxLearner) -> tuple:
    """Hashable homogeneity key: jobs with equal signatures can share
    one compiled batched program."""
    model = learner.get_model()
    params = model.get_parameters()
    leaves = jax.tree_util.tree_leaves(params)
    # dtype via np.dtype(p.dtype), NOT np.asarray(p): asarray of a jax
    # leaf copies the whole tensor to host just to read its dtype —
    # once per leaf per learner per round (caught by the sync lint).
    shapes = tuple(
        (tuple(np.shape(p)), np.dtype(p.dtype).name) for p in leaves
    )
    treedef = str(jax.tree_util.tree_structure(params))
    aux_def = str(jax.tree_util.tree_structure(model.aux_state or {}))
    return (
        repr(model.module),
        treedef,
        shapes,
        aux_def,
        learner.batch_size,
        learner.epochs,
        learner.learning_rate,
        learner._optimizer_factory,
        learner._loss_fn,
        tuple(sorted(cb.get_name() for cb in learner.callbacks)),
    )


class BatchedFitProgram:
    """Compiled ``vmap(local_fit)`` for one job signature.

    The compiled function is cached per (signature, n_batches, epochs);
    re-stacking data each round re-uses it as long as shapes repeat.
    """

    def __init__(self, learner: JaxLearner) -> None:
        module = learner._module()
        self._module = module
        self._opt = learner._tx
        self._loss_fn = learner._loss_fn
        self._has_aux = bool(learner.get_model().aux_state)
        # Gradient-tracking programs (SCAFFOLD: a callback set
        # wants_avg_grad) additionally accumulate the raw per-step
        # gradients; job_signature includes the callback names, so
        # tracking and plain jobs never share a program.
        self._track = any(
            getattr(cb, "wants_avg_grad", False) for cb in learner.callbacks
        )
        self._fns: dict[tuple[int, int], Callable] = {}

    def _build(self, epochs: int) -> Callable:
        # The program is the engine's masked vmapped local fit — ONE
        # builder shared with the pod-scale federation seam, so the
        # pool and the sharded engine can never drift numerically.
        return build_batched_fit_program(
            self._module,
            self._opt,
            self._loss_fn,
            self._has_aux,
            self._track,
            epochs,
        )

    def run(
        self,
        stacked_params: Any,
        stacked_aux: Any,
        stacked_corr: Any,
        stacked_anchor: Any,
        mus: np.ndarray,
        xs: np.ndarray,
        ys: np.ndarray,
        bmask: np.ndarray,
        epochs: int,
    ) -> tuple[Any, Any, Any, Any]:
        key = (int(xs.shape[1]), int(epochs))
        fn = self._fns.get(key)
        # Per-program shape cache: every distinct (n_batches, epochs)
        # is a fresh XLA compile — the observatory's counters are how a
        # shape-churning round schedule shows up before it hurts.
        profiling.observatory.cache_event("batched_shape_fns", hit=fn is not None)
        if fn is None:
            fn = self._fns[key] = profiling.observatory.wrap(
                self._build(epochs),
                f"batched_fit:{profiling.module_tag(self._module)}",
            )
        return fn(
            stacked_params,
            stacked_aux,
            stacked_corr,
            stacked_anchor,
            jnp.asarray(mus),
            jnp.asarray(xs),
            jnp.asarray(ys),
            jnp.asarray(bmask),
        )


_programs: dict[tuple, BatchedFitProgram] = {}


def _stack(trees: list[Any]) -> Any:
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _unstack(tree: Any, n: int) -> list[Any]:
    return [jax.tree_util.tree_map(lambda x: x[i], tree) for i in range(n)]


def run_batched_fits(
    signature: tuple, learners: list[JaxLearner]
) -> list[JaxLearner]:
    """Train every learner in ``learners`` (all sharing ``signature``)
    through one vmapped program, chunked to ``SIM_MAX_BATCH_NODES``.

    Mutates each learner's model in place via the same host-side
    lifecycle as ``JaxLearner.fit`` (prepare_fit/finish_fit). Returns
    the learners of FAILED chunks only — already-trained chunks are
    final, so the caller must not re-fit them."""
    prog = _programs.get(signature)
    profiling.observatory.cache_event("batched_programs", hit=prog is not None)
    if prog is None:
        prog = _programs[signature] = BatchedFitProgram(learners[0])

    chunk = max(int(Settings.SIM_MAX_BATCH_NODES), 1)
    failed: list[JaxLearner] = []
    for i in range(0, len(learners), chunk):
        part = learners[i : i + chunk]
        try:
            _run_chunk(prog, part)
        except Exception as e:
            logger.info(
                "simulation",
                f"Batched chunk of {len(part)} nodes failed ({e}); "
                "those nodes fall back to inline fits",
            )
            failed.extend(part)
    return failed


def _run_chunk(prog: BatchedFitProgram, learners: list[JaxLearner]) -> None:
    # Interrupts delivered before dispatch get JaxLearner's skip
    # treatment (unchanged model, zero FL weight). Once the compiled
    # round launches it is not interruptible — that is the cost of the
    # one-program batch (the inline path can still stop between epochs).
    active = []
    for ln in learners:
        if ln._interrupt.is_set():
            ln._interrupt.clear()
            logger.info(ln.get_addr(), "Fit skipped: interrupted before batch")
            ln.skip_fit()
        else:
            active.append(ln)
    learners = active
    if not learners:
        return

    epochs = learners[0].epochs
    jobs = []
    for ln in learners:
        model, initial, correction, mu, batches = ln.prepare_fit()
        xs, ys = batches.stacked(epoch=ln._round_counter * 10_000)
        ln._round_counter += 1
        jobs.append(
            {
                "learner": ln,
                "model": model,
                "initial": initial,
                "correction": correction,
                "mu": mu,
                "xs": xs,
                "ys": ys,
                "num_samples": batches.num_samples,
            }
        )

    # Pad every node's data to the chunk's max batch count; the mask
    # turns padding batches into exact no-ops inside the program.
    max_b = max(j["xs"].shape[0] for j in jobs)
    xs_l, ys_l, mask_l = [], [], []
    for j in jobs:
        nb = j["xs"].shape[0]
        pad = max_b - nb
        x, y = j["xs"], j["ys"]
        if pad:
            x = np.concatenate([x, np.zeros((pad, *x.shape[1:]), x.dtype)])
            y = np.concatenate([y, np.zeros((pad, *y.shape[1:]), y.dtype)])
        xs_l.append(x)
        ys_l.append(y)
        mask_l.append(
            np.concatenate([np.ones(nb, np.float32), np.zeros(pad, np.float32)])
        )

    # Bucket the node axis to the next power of two: group sizes drift
    # round to round (a straggler missing the batching window shrinks
    # the group by one), and every distinct vmap width is a fresh XLA
    # compile. Dummy slots replicate node 0 with an all-zero batch mask
    # (pure no-ops) and their outputs are discarded.
    bucket = 1
    while bucket < len(jobs):
        bucket *= 2
    for _ in range(bucket - len(jobs)):
        xs_l.append(xs_l[0])
        ys_l.append(ys_l[0])
        mask_l.append(np.zeros_like(mask_l[0]))

    param_trees = [
        jax.tree_util.tree_map(jnp.copy, j["model"].get_parameters())
        for j in jobs
    ]
    aux_trees = [
        jax.tree_util.tree_map(jnp.copy, j["model"].aux_state or {})
        for j in jobs
    ]
    corr_trees = [j["correction"] for j in jobs]
    # Anchors (round-start params for the proximal pull) must be
    # separate buffers from stacked_params — those are donated.
    anchor_trees = [j["initial"] for j in jobs]
    mus = [float(j["mu"]) for j in jobs]
    for _ in range(bucket - len(jobs)):
        param_trees.append(param_trees[0])
        aux_trees.append(aux_trees[0])
        corr_trees.append(corr_trees[0])
        anchor_trees.append(anchor_trees[0])
        mus.append(0.0)
    stacked_params = _stack(param_trees)
    stacked_aux = _stack(aux_trees)
    stacked_corr = _stack(corr_trees)
    stacked_anchor = _stack(anchor_trees)
    xs_s: Any = np.stack(xs_l)
    ys_s: Any = np.stack(ys_l)
    mask_s: Any = np.stack(mask_l)
    mus_s: Any = np.asarray(mus, np.float32)  # host-sync: mus is a host list

    # Pod-scale path (Settings.SHARD_NODES): place the stacked node
    # axis over the local `nodes` mesh — the pow-2 bucket above divides
    # a 2^k-chip host, so every chip trains an equal shard of the
    # chunk's nodes SPMD inside the one compiled program.
    mesh = maybe_nodes_mesh(bucket)
    if mesh is not None:
        sharding = federation_sharding(mesh)
        stacked_params, stacked_aux, stacked_corr, stacked_anchor = (
            jax.device_put(t, sharding)
            for t in (stacked_params, stacked_aux, stacked_corr, stacked_anchor)
        )
        xs_s, ys_s, mask_s = (
            jax.device_put(jnp.asarray(a), sharding)
            for a in (xs_s, ys_s, mask_s)
        )
        mus_s = jax.device_put(jnp.asarray(mus_s), sharding)

    # Round attribution: the chunk's dispatch gap and device compute
    # are charged to EVERY participating node — each node's round
    # blocked on this one program for its full duration.
    prof = profiling.rounds.enabled()
    t0 = time.monotonic() if prof else 0.0
    new_params, new_aux, losses, gsums = prog.run(
        stacked_params,
        stacked_aux,
        stacked_corr,
        stacked_anchor,
        mus_s,
        xs_s,
        ys_s,
        mask_s,
        epochs,
    )
    if prof:
        t1 = time.monotonic()
        jax.block_until_ready(losses)
        t2 = time.monotonic()
        for j in jobs:
            addr = j["learner"].get_addr()
            profiling.rounds.add(addr, "dispatch", t1 - t0)
            profiling.rounds.add(addr, "train", t2 - t1)
    # host-sync: ONE deliberate sync per chunk — the window is over and
    # every learner's finish_fit/metrics below consume losses on host.
    losses = np.asarray(losses)

    params_per_node = _unstack(new_params, len(jobs))
    aux_per_node = _unstack(new_aux, len(jobs))
    gsum_per_node = _unstack(gsums, len(jobs)) if prog._track else None
    for i, j in enumerate(jobs):
        ln, model = j["learner"], j["model"]
        n_steps = j["xs"].shape[0] * epochs
        avg_grad = None
        if gsum_per_node is not None:
            # The masked gsum summed only REAL batches; divide by the
            # node's own step count, not the padded chunk max.
            inv = jnp.float32(1.0 / max(n_steps, 1))
            avg_grad = jax.tree_util.tree_map(
                lambda g: g * inv, gsum_per_node[i]
            )
        ln.finish_fit(
            model,
            j["initial"],
            params_per_node[i],
            aux_per_node[i] if model.aux_state else None,
            n_steps,
            j["num_samples"],
            avg_grad=avg_grad,
        )
        if ln._in_experiment():
            logger.log_metric(
                ln.get_addr(), "train_loss", float(losses[i]), step=epochs - 1
            )
        # Same fit-seam loss tap as JaxLearner.fit (losses is already a
        # host array — no added device sync).
        if Settings.LEDGER_ENABLED:
            ledger.convergence.observe_loss(
                ln.get_addr(),
                (ln._round_counter - 1) * 10_000 + epochs - 1,
                float(losses[i]),
            )
        logger.debug(
            ln.get_addr(),
            f"batched fit ({len(jobs)} nodes): loss={float(losses[i]):.4f}",
        )
