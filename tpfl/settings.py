"""Global configuration.

Mirrors the surface of the reference's ``p2pfl/settings.py`` (class-level
constants, mutable at runtime before nodes start) while adding the
profile system the reference scatters across ``utils/utils.py:39`` and
``examples/mnist.py:43``.  Reference: ``p2pfl/settings.py:28-153``.

Values are read at use-time (not captured at import) everywhere in tpfl,
so mutating ``Settings.X`` between experiments is safe — this fixes the
import-capture footgun noted in the reference (``examples/mnist.py:262``).
"""

from __future__ import annotations

import os
from typing import Any


class Settings:
    """Class-level configuration constants, mutable before node start."""

    # --- gRPC / transport ---
    GRPC_TIMEOUT: float = 10.0
    """Timeout (s) for unary RPCs (handshake/disconnect/send)."""

    MAX_MESSAGE_SIZE: int = 1024 * 1024 * 1024
    """Max gRPC message size (1 GiB) — parity with grpc_server.py:65."""

    ELECTION: str = "vote"
    """Train-set election mode. "vote" (default): the reference's
    random-weight vote — every node floods a vote and tallies
    (vote_train_set_stage.py:79-171); O(N²) messages per round plus a
    VOTE_TIMEOUT wait whenever any vote is missing. "hash":
    deterministic sortition — rank candidates by
    H(exp_name, round, addr) and take the top TRAIN_SET_SIZE; zero
    messages, zero wait, and all nodes agree whenever their membership
    views agree (digest heartbeats give full view before learning
    starts). The per-round set still rotates pseudo-randomly with the
    round number. Recommended for 100+ node federations.

    Adversarial model: the rank mixes in a per-experiment random
    beacon (hash of the initiator's init-model bytes, carried by the
    StartLearning broadcast — stages.base_node.election_rank), so an
    address committed BEFORE the experiment starts cannot be ground to
    rank top-K: the beacon is unknown at address-choice time, and a
    fixed address's election frequency under random beacons is uniform
    (tested). What remains is a pre-commitment assumption: an
    adversary that observes the beacon and only THEN joins with a
    freshly ground address still wins, and the initiator itself could
    grind init weights to favor an address it controls. Deployments
    that cannot pre-commit membership (or trust the initiator) should
    keep "vote" (each elector samples with private randomness) — the
    global default — and pair hash election with a robust aggregator
    (tpfl.learning.aggregators.robust) when they need both scale and
    poisoning tolerance. See docs/protocol.md."""

    INIT_GOSSIP_STATIC_EXIT_S: float = 30.0
    """Wall-clock quiet window before the init-weights diffusion stops
    pushing to silent neighbors (StartLearningStage). Iteration-count
    exits proved too aggressive at 500-node scale, where the
    StartLearning flood itself takes tens of seconds to spread."""

    GRPC_SERVER_WORKERS: int = 16
    """gRPC server handler threads. The reference pins 2
    (grpc_server.py:67); a multislice host fanning out to tens of peers
    serializes handler work at that width — raise for dense hubs."""

    # --- transport resilience (retry / circuit breaker) ---
    RETRY_MAX_ATTEMPTS: int = 3
    """Total attempts per outbound send (unary and streamed): 1 = the
    reference's fire-once behavior. Retries are safe — control messages
    dedup by hash at the receiver, weight payloads by round/contributor
    bookkeeping — so a duplicate delivery from a retried send that
    actually arrived is absorbed."""

    RETRY_BASE_DELAY: float = 0.05
    """Backoff before retry k is ``min(RETRY_MAX_DELAY,
    RETRY_BASE_DELAY * 2**k)`` scaled by equal jitter in [0.5, 1.5)
    drawn from a per-node seeded RNG (deterministic under
    Settings.SEED)."""

    RETRY_MAX_DELAY: float = 2.0
    """Cap on a single backoff sleep (seconds)."""

    BREAKER_THRESHOLD: int = 3
    """Consecutive *failed sends* (each already retried
    RETRY_MAX_ATTEMPTS times) to a neighbor before its circuit opens:
    the peer is marked suspect, evicted from the table, and no longer
    costs send budget. The reference evicts on the FIRST failed send
    (grpc_client.py:176-183), which a single lost packet can trigger."""

    BREAKER_PROBE_PERIOD: float = 10.0
    """Seconds between half-open reconnect probes to a suspect peer
    (rides the heartbeater cadence, so the effective period is
    ``max(BREAKER_PROBE_PERIOD, HEARTBEAT_PERIOD)``). A successful
    probe handshake — or an incoming beat from the peer — closes the
    circuit and re-admits it."""

    # --- logging ---
    LOG_LEVEL: str = "INFO"
    FILE_LOGGER: bool = True
    LOG_DIR: str = "logs"
    LOG_FILE_MAX_BYTES: int = 10_000_000
    LOG_FILE_BACKUP_COUNT: int = 3
    ASYNC_LOGGER: bool = True

    # --- simulation ---
    DISABLE_SIMULATION: bool = False
    """When True, learners run inline instead of through the batching
    pool (tpfl.simulation.SuperLearnerPool)."""

    SIM_WORKERS: int = 0
    """Threads for the pool's non-batchable fallback fits; 0 = cpu_count."""

    SIM_BATCH_WINDOW: float = 0.2
    """Seconds the pool waits after the first fit submission for the
    rest of the train set to arrive before dispatching the batch."""

    SIM_BATCH_MAX_WAIT: float = 5.0
    """Upper bound on holding a hinted fit group open (a straggler
    later than this trains in its own dispatch)."""

    SIM_MAX_BATCH_NODES: int = 128
    """Chunk size for the vmapped batched fit (memory bound: params ×
    chunk nodes resident). SURVEY 'hard parts': 1000-node sim."""

    SIM_PROCESS_ISOLATION: bool = False
    """When True, the pool's fallback fits run in spawned worker
    processes (tpfl.simulation.isolated): a crashing learner / native
    segfault kills one worker, not the whole federation — the
    reference's Ray-actor isolation property (actor_pool.py:203-357),
    opt-in because process round-trips cost what the thread pool
    avoids. Scope: plain JaxLearner fits (no callbacks/aux); other
    jobs stay on the thread pool."""

    # --- heartbeat ---
    HEARTBEAT_PERIOD: float = 2.0
    HEARTBEAT_TIMEOUT: float = 5.0

    # --- gossip (control plane) ---
    GOSSIP_PERIOD: float = 0.1
    TTL: int = 10
    GOSSIP_MESSAGES_PER_PERIOD: int = 100
    AMOUNT_LAST_MESSAGES_SAVED: int = 100

    # --- gossip (model data plane) ---
    GOSSIP_MODELS_PERIOD: float = 1.0
    GOSSIP_MODELS_PER_ROUND: int = 2
    GOSSIP_EXIT_ON_X_EQUAL_ROUNDS: int = 10
    # Downcast float parameters on the wire ("bfloat16"/"float16"; None
    # = exact). Halves model-gossip bytes over DCN; receivers restore
    # their model's own dtype on set. Lossy (~3 decimal digits for
    # bf16) — FedAvg tolerates it, leave None for exact-repro runs.
    # Applies to the DENSE codec only; WIRE_CODEC supersedes it.
    WIRE_DTYPE: str | None = None

    # --- wire codec (model payload compression) ---
    WIRE_CODEC: str = "dense"
    """Model-payload wire codec (tpfl.learning.compression): "dense"
    (v1 envelope, exact, what old peers decode), or a '+'-composed
    stack of "quant8" (int8 symmetric per-leaf quantization, jitted),
    "topk" (top-k magnitude sparsification, index+value packing) and
    one entropy coder ("zlib", or "zstd" when the optional zstandard
    package is installed). E.g. "quant8+zlib". Validated at use time —
    unknown names raise ValueError. Lossy codecs are within FedAvg /
    SCAFFOLD convergence noise on the digits/CIFAR paths (seeded A/B
    in bench.py) at ≥4x fewer payload bytes."""

    WIRE_TOPK_FRAC: float = 0.05
    """Fraction of entries per leaf the "topk" codec keeps (by
    magnitude). Only read when WIRE_CODEC includes "topk"."""

    WIRE_ENTROPY_LEVEL: int = 1
    """Compression level for the entropy stage (zlib/zstd). 1 favors
    encode throughput — the gossip hot path encodes once per model
    version but at a 1000-node hub every CPU cycle is contended."""

    WIRE_DELTA: bool = False
    """Residual (delta) gossip: once a round's aggregate is adopted it
    becomes a BASE (tpfl.learning.compression.BaseCache); the next
    round's full-model pushes to peers that acknowledged that base
    (nei_status == round-1) carry only ``current - base``, which
    quantizes/compresses far smaller than the full weights. A peer
    without the base nacks (``codec_nack``) and the sender falls back
    to dense for it — old peers and fresh joiners keep working."""

    WIRE_CHUNK_SIZE: int = 256 * 1024
    """gRPC payload chunking threshold AND chunk size (bytes). Messages
    larger than this stream as CRC-tagged chunks over a dedicated
    streaming RPC instead of one multi-MB unary frame, so heartbeats
    and votes no longer queue behind a model transfer on the wire
    (head-of-line). 0 disables chunking."""

    # --- zero-copy model plane ---
    WIRE_FORMAT: int = 3
    """Dense model-payload envelope version. 3 (default): the zero-copy
    layout — msgpack header (dtype/shape/offset table) + ONE contiguous
    payload staged through the node's BufferPool; encode writes each
    leaf's bytes exactly once, decode returns read-only memoryview-
    backed array views with zero per-leaf copies. 1: the legacy dense
    msgpack map, for federations that still contain pre-v3 peers (every
    tpfl node decodes v1, v2 AND v3 regardless of this setting — it
    only selects what WE emit). Compressed codecs (WIRE_CODEC) emit v2
    envelopes independently of this knob."""

    INPROC_ZERO_COPY: bool = False
    """In-memory transport fast path: hand model payloads between
    co-located nodes BY REFERENCE (tpfl.learning.serialization
    .InprocModelRef) — no encode, no decode, no bytes at all. Leaves
    are frozen (read-only numpy views; jax arrays are immutable) and
    contributor metadata is copied, so neither side can mutate the
    other (tests/test_zero_copy.py asserts non-aliasing under both
    settings). gRPC federations are unaffected: the flag only takes
    effect on transports that declare ZERO_COPY_INPROC, and the wire
    bytes of every gRPC payload stay identical either way. Off by
    default for reference parity; the scale profile enables it — at
    1000 single-host nodes the encode/decode of every gossip push was
    memcpy the receiver shares an address space with."""

    BUFFER_POOL_BUFFERS: int = 8
    """Max reusable serialization buffers a BufferPool retains
    (tpfl.learning.bufferpool). The steady state is one buffer per
    node, reused every encode; extras cover concurrent encode paths
    (gossiper + relay + init diffusion)."""

    BUFFER_POOL_MAX_BYTES: int = 256 * 1024 * 1024
    """Cap on the total bytes a BufferPool may keep pooled. Returned
    buffers that would exceed it are freed instead of pooled."""

    # --- SSL / mTLS ---
    USE_SSL: bool = False
    CA_CRT: str = ""
    SERVER_CRT: str = ""
    SERVER_KEY: str = ""
    CLIENT_CRT: str = ""
    CLIENT_KEY: str = ""

    # --- FL round protocol ---
    TRAIN_SET_SIZE: int = 4
    VOTE_TIMEOUT: float = 60.0
    AGGREGATION_TIMEOUT: float = 300.0
    WAIT_HEARTBEATS_CONVERGENCE: float = 0.2

    # --- asynchronous buffered rounds (FedBuff-style) ---
    ASYNC_ROUNDS: bool = False
    """Master gate for the asynchronous round lifecycle
    (stages.base_node.AsyncRoundStage): every live peer trains
    continuously and contributes whenever its fit finishes — no vote
    election and no slowest-trainer barrier. Each node's aggregator
    folds arrivals as a buffered FedBuff-style round
    (``Aggregator.set_nodes_to_aggregate(async_k=...)``): a
    contribution trained from model-version ordinal ``v`` folding into
    round ``r`` carries staleness ``τ = r - v`` and weight
    ``num_samples / (1 + τ)**ASYNC_STALENESS_EXP``; the round closes on
    buffer-full (``ASYNC_BUFFER_K`` distinct contributors) or the
    ``ASYNC_ROUND_DEADLINE`` failsafe — a dead trainer costs nothing
    instead of AGGREGATION_TIMEOUT (the quorum-degradation economics,
    without the barrier that made them necessary). Off (default):
    the synchronous vote/train/wait lifecycle, reference parity.
    See docs/protocol.md "Asynchronous buffered rounds"."""

    ASYNC_BUFFER_K: int = 4
    """Contributions (distinct contributors) that close an async
    round's buffer — FedBuff's K. Clamped per round to the live peer
    count; 1 is the degenerate fully-sequential buffer (every single
    contribution makes a round)."""

    ASYNC_STALENESS_EXP: float = 0.5
    """Staleness-decay exponent: a contribution ``τ`` versions stale
    folds at weight ``w(τ) = 1/(1+τ)**exp`` times its sample count.
    0 disables staleness discounting (pure buffered FedAvg); 0.5 is
    FedBuff's ``1/sqrt(1+τ)``; larger values silence stragglers
    faster."""

    ASYNC_ROUND_DEADLINE: float = 30.0
    """Failsafe (s) on an async round staying open short of
    ASYNC_BUFFER_K contributions: at the deadline the round closes
    with whatever the buffer holds (``round_deadline`` flight event +
    ``tpfl_agg_deadline_total``). An EMPTY buffer at the deadline
    fails open loudly — the round stays open (there is nothing to
    aggregate) and the stage re-arms the deadline."""

    ASYNC_SERIALIZED: bool = True
    """Deterministic async discipline (test/standalone profiles):
    arrivals buffer without folding and the round-close fold runs in a
    serialized deterministic order — schedule order when a seeded
    :class:`tpfl.communication.faults.AsyncSchedule` is attached to
    the aggregator (the reorder-buffer admission that makes same-seed
    runs byte-identical, bench's async tier), else canonical
    (contributor-sorted) order. False (scale profile): free-running —
    contributions fold eagerly in arrival order (AGG_STREAM_EAGER
    semantics), maximum throughput, no reproducibility guarantee."""

    ASYNC_ADAPTIVE: bool = False
    """Adaptive async control plane (tpfl.learning.async_control
    .AsyncController): when on, each node tunes its EFFECTIVE buffer K
    and round deadline per round from the observed inter-arrival and
    staleness distributions (EWMA over per-round order-invariant
    summaries + the ASYNC_CTL_QUANTILE inter-arrival quantile), bounded
    by [ASYNC_K_MIN, ASYNC_K_MAX] and (0, ASYNC_ROUND_DEADLINE].
    ASYNC_BUFFER_K / ASYNC_ROUND_DEADLINE become the starting point and
    the deadline ceiling instead of static values. In serialized mode
    the controller's observations derive from the seeded AsyncSchedule
    VIRTUAL clock (arrival ordinals without one), so same-seed runs
    keep byte-identical K/deadline trajectories at every node; free-
    running observations use the monotonic clock. Off (default): the
    PR-10 static knobs, bit-identical behavior."""

    ASYNC_K_MIN: int = 2
    """Lower bound on the adaptive controller's effective buffer K
    (ASYNC_ADAPTIVE). K=1 degenerates to a fully-sequential buffer
    where any single flooder makes a round — 2 keeps at least one
    honest arrival in every defended round's fold."""

    ASYNC_K_MAX: int = 16
    """Upper bound on the adaptive controller's effective buffer K
    (further clamped per round to the live fleet size). A K at the
    fleet size is the synchronous barrier again — the controller grows
    toward this only while buffers fill fast and staleness stays low."""

    ASYNC_CTL_EWMA: float = 0.3
    """EWMA smoothing factor for the controller's per-round observation
    summaries (inter-arrival quantile, mean staleness, fill time):
    ``s <- (1-a)*s + a*x``. Higher = reacts faster to fleet changes,
    lower = steadier knobs. Only read when ASYNC_ADAPTIVE."""

    ASYNC_CTL_QUANTILE: float = 0.9
    """Inter-arrival quantile the controller's deadline targets: the
    effective deadline covers ``K`` arrivals at this quantile of the
    observed inter-arrival distribution (x a fixed 4x safety margin),
    clamped to ASYNC_ROUND_DEADLINE. 0.9 tolerates a 10% arrival tail
    without deadline-closing the round. Only read when ASYNC_ADAPTIVE."""

    ASYNC_STALENESS_MAX: int = 16
    """Staleness plausibility bound, two consumers: (1) the robust
    aggregators (Krum/MultiKrum/TrimmedMean) REJECT buffered candidates
    whose ``τ`` exceeds it at finalize (boundary τ == max is kept;
    all-rejected fails open loudly — a defense never bricks a round);
    (2) the anomaly scorer flags contributions past it — or whose
    version ordinal REGRESSES below one the same peer already
    contributed — as ``stale_flood``, the buffer-stuffing attack
    signature (tpfl.attacks.plan: stale_flood / withhold_replay), which
    the quarantine engine then excludes like any other anomaly class.
    Negative disables both. Honest stragglers sit at single-digit τ in
    every measured configuration; 16 is far past the staleness-weight
    floor (w(16) ≈ 0.24 at the default exp) where a contribution stops
    mattering anyway."""

    ASYNC_UNTAGGED_POLICY: str = "fresh"
    """Freshness semantics for UNTAGGED contributions
    (``Message.version == -1``: pre-async peers, or a spoofing
    adversary omitting the tag to bypass staleness weighting):
    "fresh" — τ=0, full weight (reference-parity default: a pre-async
    peer is not penalized); "max-stale" — τ = ASYNC_STALENESS_MAX, the
    most-discounted weight that still folds (the scale default:
    untagged mass cannot dominate a buffer); "reject" — refused at
    intake with ``tpfl_agg_untagged_rejected_total`` (strict
    deployments where every peer is known to tag). The policy applies
    to the staleness weight, the robust candidates' τ, and the
    quarantine/ledger window the same way — one resolved τ per
    contribution. Sync rounds ignore it (every sync contribution is
    τ=0 by construction)."""

    # --- aggregation (streaming accumulators) ---
    AGG_STREAM_EAGER: bool = True
    """Fold contributions into the aggregator's on-device running
    accumulator AS THEY ARRIVE (Aggregator.accumulate/finalize) instead
    of reducing everything at round close. Peak memory for mean-style
    aggregators (FedAvg/FedProx/SCAFFOLD) is O(1 model) either way —
    the batch path also folds sequentially with buffer donation — but
    the eager path moves the reduce off the round's critical tail: by
    the time coverage completes, the aggregate is one finalize away.
    Trade-off: the fold runs in ARRIVAL order, so bit-exact
    run-to-run reproducibility of the aggregate (float addition is not
    associative) requires False, which folds the held models in
    canonical sorted order at close instead. The test and standalone
    profiles set False (exactness/reference parity first); the scale
    profile sets True."""

    AGG_MEDIAN_RESERVOIR: int = 64
    """FedMedian's streaming state keeps at most this many contributions
    (seeded reservoir sampling beyond it) — an exact median up to the
    cap, an unbiased sampled median past it, and bounded memory at any
    federation size."""

    ROUND_QUORUM: float = 1.0
    """Fraction of the *live* train set whose contributions close a
    round's aggregation. 1.0 (default) = reference behavior: every
    expected contributor must report (or the deadline/stall fires).
    When heartbeat loss evicts a train-set member mid-round the
    expected set shrinks to the live members
    (Aggregator.remove_dead_nodes), so a crashed trainer no longer
    costs every peer the full AGGREGATION_TIMEOUT; ROUND_QUORUM < 1.0
    additionally lets aggregation close before slow-but-alive members
    report — use with care: unlike AGGREGATION_STALL it does not wait
    for intake to go quiet, so an aggressive quorum can fracture the
    aggregate mid-exchange exactly like an undersized stall window."""

    # --- observability ---
    RESOURCE_MONITOR_PERIOD: float = 1.0

    TELEMETRY_ENABLED: bool = False
    """Master gate for hop-level distributed tracing
    (tpfl.management.tracing): when on, every model-payload encode
    mints a 16-byte trace id that rides the wire envelope (v3 header
    ``tid`` extension; v1/v2 peers still decode) and the in-proc
    ``InprocModelRef``, and every gossip hop, retry, breaker trip,
    decode, and aggregation fold becomes a span in the per-node flight
    recorder — reconstructable across nodes into a round timeline by
    ``tools/traceview.py``. Off by default: the metrics REGISTRY
    (``logger.metrics``) always records (cheap per-thread dict
    updates), but span minting/recording is gated here — measured <5%
    rounds/sec overhead when on (bench.py telemetry tier), zero when
    off. Read at use time, so it can be toggled between experiments."""

    TELEMETRY_RING: int = 512
    """Flight-recorder capacity: the last N spans/events retained PER
    NODE (tpfl.management.telemetry.FlightRecorder). The ring is what
    ``Node.stop()`` and the chaos harness dump on crash or quorum
    degradation — size it to cover at least one full round of spans
    for post-mortems (a 4-node round is a few hundred spans)."""

    TELEMETRY_MAX_LABELSETS: int = 64
    """Label-cardinality cap per metric in the registry
    (tpfl.management.telemetry.MetricsRegistry): label sets beyond the
    cap collapse into a reserved ``{"overflow": "true"}`` series
    instead of growing without bound — a per-peer label on a
    1000-node federation must not turn the registry into the leak it
    exists to observe."""

    TELEMETRY_DUMP_DIR: str = ""
    """Directory for flight-recorder crash dumps (JSON, one file per
    (node, reason)). Empty (default) disables file dumps — the ring
    still records and ``logger.metrics``/``FlightRecorder.snapshot``
    stay queryable in-process. Set by the chaos harness / bench so
    every injected crash and quorum degradation is post-mortem-able."""

    METRIC_MAX_POINTS: int = 4096
    """Per-series point cap in the local/global metric stores
    (tpfl.management.metric_storage): a series keeps the most recent N
    (step, value) / (round, value) points, evicting oldest-first. An
    unbounded per-step series on a long-running node was the only
    unbounded memory left in the management layer."""

    FLEETOBS_SNAPSHOT_PERIOD: float = 0.0
    """Cadence (s) of the fleet-observatory snapshot publisher
    (tpfl.management.fleetobs.FleetPublisher): every period the
    process' MetricsRegistry is folded and written atomically as
    ``fleetsnap-<origin>.json`` into ``FLEETOBS_DIR``, where rank 0
    (or any scraper) folds all ranks' snapshots into ONE fleet
    registry (``MetricsRegistry.merge`` semantics, ``origin=<rank>``
    labels) served by ``MetricsHTTPServer`` ``/fleet.json``. 0.0
    (default) = no publisher thread; the crosshost receipt path still
    embeds a one-shot snapshot per worker regardless (that path is
    pull-per-run, not periodic)."""

    FLEETOBS_DIR: str = ""
    """Directory the fleet snapshot publisher writes to and the fleet
    fold reads from (one ``fleetsnap-<origin>.json`` per process,
    written tmp+rename so readers never see a torn document). Empty
    (default) disables file publishing even when
    ``FLEETOBS_SNAPSHOT_PERIOD`` is set — multi-host deployments point
    every rank at one shared path (NFS/GCS-fuse), single-host
    simulations at any tmp dir."""

    SLO_TARGETS: str = ""
    """Declared service-level objectives the live watchdog
    (tpfl.management.fleetobs.SLOWatchdog) evaluates over the metrics
    registry: semicolon-separated clauses ``expr op value`` with
    ``expr`` one of ``rate(counter)`` (per-second rate between
    evaluations), ``gauge(name)`` (latest value, summed across label
    sets), ``ratio(a, b)`` (counter ``a`` per counter ``b`` —
    e.g. DCN bytes per engine round) and ``op`` one of ``< <= > >=``.
    Example: ``"rate(tpfl_engine_rounds_total) >= 2.0;
    gauge(tpfl_engine_idle_gap_seconds) <= 0.5"``. Signals are
    EWMA-smoothed (``SLO_EWMA``); ``SLO_BREACH_WINDOWS`` consecutive
    violating evaluations emit a ``slo_breach`` flight event and bump
    ``tpfl_slo_breach_total`` — bench's offline baseline gate brought
    into running federations. Empty (default) = watchdog idle."""

    SLO_EWMA: float = 0.3
    """EWMA smoothing factor for SLO watchdog signals (weight of the
    NEWEST observation; 1.0 = no smoothing). Smoothing keeps a single
    slow scrape interval or GC pause from counting as a breach window
    — the watchdog is after sustained regressions, not blips."""

    SLO_BREACH_WINDOWS: int = 2
    """Consecutive violating evaluations before a breach fires (the
    ``slo_breach`` flight event + ``tpfl_slo_breach_total`` counter).
    The streak resets on any healthy evaluation; after firing, the
    breach re-arms only once the target goes healthy again — a
    sustained breach is ONE event, not one per evaluation."""

    GOSSIP_METRICS: bool = True
    """Broadcast eval metrics to the federation after each round
    (reference MetricsCommand behavior). At N nodes each broadcast
    TTL-floods through every node — O(N²) handler work per round for
    observability only — so the scale profile turns it off (metrics
    still log locally; the experiment result does not depend on it)."""

    AGGREGATION_STALL: float | None = None
    """When set, a trainer whose aggregation intake has gone quiet for
    this many seconds (holding at least one contribution, full
    coverage not reached) proceeds with the partial aggregate instead
    of waiting out AGGREGATION_TIMEOUT. None (default) = reference
    behavior: wait the full timeout. The scale profile sets 60.0 —
    at 1000 nodes an elected-but-unready peer otherwise costs every
    trainer the entire timeout each round (measured: the dominant
    round wall-clock term).

    Sizing: the window must comfortably exceed the worst-case
    single-payload delivery time (serialize + wire + decode + jitted
    add_model of one partial model), or the stall fires MID-EXCHANGE
    and fractures the aggregate — a 30 s stall did exactly that at
    1000 nodes (docs/deployment.md). A lossy WIRE_CODEC (e.g.
    "quant8+zlib", ~4-5x fewer payload bytes) shrinks that worst case
    proportionally, buying stall-window headroom at the same
    setting. Timed on the monotonic clock (Aggregator.stalled), so
    NTP steps cannot suppress or prematurely fire the exit."""

    ROUND_WAIT_POLL: float = 0.5
    """Upper bound (s) on the round-result wait's poll interval
    (stages._await_round_result). FullModel arrival wakes waiters
    instantly via the event; this bounds only how fast early-stop /
    local-coverage conditions are noticed. The scale profile widens it
    to 2.0 — hundreds of waiters waking 2x/s are a measurable GIL tax
    at 1000 in-process nodes."""

    # --- device-plane profiling ---
    PROFILING_ENABLED: bool = False
    """Master gate for the device-plane performance observatory
    (tpfl.management.profiling): per-call recompile detection on the
    wrapped jit seams (CompileObservatory), per-round wall-clock
    attribution spans (RoundProfiler: train/dispatch/fold/gossip/
    host_other), and the block_until_ready dispatch/compute split in
    the learner. Off by default — disabled profiling is one attribute
    read per instrumented site, adds ZERO device dispatches, and costs
    no measurable rounds/sec (bench.py's profiling tier A/B); enabled
    overhead is budgeted ≤5% like the telemetry tier. The always-cheap
    registry side (compiled-cache hit/miss counters and size gauges,
    HBM gauges) records regardless, per the PR-5 rule. Read at use
    time, so it can be toggled between experiments."""

    PROFILING_RECOMPILE_WARN: int = 8
    """Distinct abstract argument signatures (shapes/dtypes/statics)
    one wrapped program may accrete before the observatory flags a
    RECOMPILE STORM (flight-ring event + log warning). Every distinct
    signature is a fresh XLA compile — shape churn that defeats the
    jit cache is the silent killer of steady-state throughput (the
    vmap-width bucketing in simulation/batched_fit exists for exactly
    this reason). Only read when PROFILING_ENABLED."""

    PROFILING_TRACE_DIR: str = ""
    """When set, federation runs wrap the experiment (StartLearning →
    experiment finish) in a ``jax.profiler`` trace written here —
    bench.py's opt-in ``--profile``, promoted to ANY run: the CLI's
    ``tpfl experiment run --profile DIR`` sets this via the
    ``TPFL_PROFILING_TRACE_DIR`` environment override. One process-wide
    trace at a time (in-process federations share the profiler); view
    with TensorBoard/xprof. Empty (default) disables."""

    # --- learning-plane observatory (contribution ledger) ---
    LEDGER_ENABLED: bool = False
    """Master gate for the learning-plane observatory
    (tpfl.management.ledger): per-contribution update statistics
    (L2 norm, per-leaf norm profile, cosine vs the round-start
    reference and vs the running update mean — one fused jitted
    reduction per accepted contribution, O(1) memory), the bounded
    per-node ContributionLedger ring, the ConvergenceMonitor
    (global-model delta norm + loss-trajectory slope), and the
    AnomalyScorer's sign-flip / norm-outlier detection. Off by
    default — disabled, every tap is one attribute read and adds ZERO
    device dispatches (bench.py's ledger tier off/on A/B is the
    receipt); enabled overhead is budgeted <5% rounds/sec like
    telemetry/profiling. Detection is observational: flags never
    change aggregation results. Read at use time."""

    LEDGER_RING: int = 1024
    """Contribution-ledger capacity: the last N contribution records
    retained PER NODE (the ring is also the anomaly scorer's
    running-baseline window, so size it to cover several rounds of
    the expected train set)."""

    LEDGER_ANOMALY_Z: float = 6.0
    """Robust z-score (vs the ledger window's median/1.4826·MAD) of a
    contribution's update L2 norm at or above which it is flagged a
    norm outlier (additive-noise signature: N(0, std) noise over d
    parameters adds std·√d of update norm — tens of sigmas at the
    attack-harness defaults, while honest updates cluster within a
    few). Only applied once LEDGER_ANOMALY_MIN_N samples exist."""

    LEDGER_ANOMALY_COS: float = 0.0
    """Cosine similarity against the round-start reference at or below
    which a contribution is flagged sign-flipped (a negated model sits
    at ≈ -1; honest contributions at ≈ +1 — the margin is wide, and
    the test needs no history, so round 0 already flags)."""

    LEDGER_ANOMALY_MIN_N: int = 4
    """Minimum single-contribution samples in the scorer's window
    before the norm-outlier z-test applies (a median/MAD over fewer
    points is noise; the cosine test is exempt — it needs no
    baseline)."""

    LEDGER_CONVERGENCE_WINDOW: int = 5
    """Trailing window (rounds/fits) for the ConvergenceMonitor's
    plateau/divergence tests and the loss-trajectory slope."""

    # --- active Byzantine defense (quarantine) ---
    QUARANTINE_ENABLED: bool = False
    """Master gate for the active defense plane
    (tpfl.management.quarantine): every single-contributor model at the
    aggregation intake is live-scored by the learning-plane ledger's
    AnomalyScorer (one fused jitted reduction, the PR-7 math) BEFORE it
    can fold — contributions flagged sign-flip / norm-outlier are
    excluded from the aggregate (kept as coverage-only passengers so
    the round still closes), the flagged peer enters quarantine, and
    subsequent clean contributions earn re-admission after
    QUARANTINE_PROBATION_ROUNDS. Requires the ledger's round state:
    enabling this activates the ledger's open-round/scoring taps even
    when LEDGER_ENABLED is off (the observational knob only gates the
    passive record path). Off by default — disabled, the intake is one
    attribute read; enabled overhead is budgeted within the shared 5%
    rounds/sec envelope (bench.py's byzantine tier off/on A/B). Unlike
    the ledger, quarantine is NOT observational: verdicts change what
    aggregates. Read at use time."""

    QUARANTINE_PROBATION_ROUNDS: int = 2
    """Rounds a quarantined peer's contributions must score clean
    (strictly more than this many rounds past its last flagged round)
    before it is re-admitted to the fold. Contributions during
    probation are still scored — they earn the streak — but stay
    excluded. A flagged contribution during probation re-arms the
    window from its round."""

    AGG_ROBUST_BUFFER: int = 64
    """Candidate-buffer budget for the streaming robust aggregators
    (Krum / MultiKrum / TrimmedMean): each keeps at most this many
    per-round candidates on device — a flat float32 projection matrix
    for Krum scoring, a per-leaf stacked reservoir for the trimmed
    mean — with seeded reservoir sampling past the cap (exact up to
    the cap, an unbiased sample beyond it), so peak memory is
    O(buffer), not O(contributor count)."""

    ATTACK_NOISE_STD: float = 0.1
    """Default standard deviation for the additive-noise attack when an
    AttackPlan rule does not set one (tpfl.attacks.plan; reference
    exp_SAVE3.txt:213-223 uses 0.1). Bench/test machinery, not a
    production knob."""

    # --- pod-scale federation engine (node-axis sharding) ---
    SHARD_NODES: bool = False
    """Master gate for automatic node-axis sharding in the federation
    engine (tpfl.parallel.engine): when True and more than one
    accelerator is visible, engine consumers that do not pin a mesh
    explicitly — the batched-fit pool's vmapped chunks
    (``engine.maybe_nodes_mesh``) and engines built with
    ``mesh="auto"`` — spread the stacked node axis over a ``nodes``
    mesh of the local devices, with the gossip exchange + FedAvg fold
    lowered to ``lax.psum`` collectives over ICI. Off (default): one
    device, the reference-parity layout. Determinism caveat: a FIXED
    device count is part of the reproducibility key — same seed at the
    same device count is byte-identical, but changing the device count
    regroups the fold's partial sums (docs/scaling.md)."""

    SHARD_DEVICES: int = 0
    """Cap on the devices the SHARD_NODES mesh may span: 0 (default) =
    all local devices, N > 0 = the first N. Lets a multi-tenant host
    pin the federation to a slice of the chips."""

    SHARD_MODEL: int = 1
    """Model-parallel axis size of the engine's auto mesh
    (``tpfl.parallel.engine.auto_mesh``): 1 (default) = the 1D
    ``nodes`` mesh — engine programs lower byte-identical to the
    pre-2D path; M > 1 = a 2D ``nodes x model`` mesh (``nodes`` =
    allowed devices / M, which must divide) where each node's
    parameters/optimizer state shard over the ``model`` axis per the
    ``SHARD_LAYOUT`` per-leaf PartitionSpec policy
    (``tpfl.parallel.mesh.SpecLayout``) — federate models bigger than
    one chip's HBM. The fold still reduces over ``nodes`` only; each
    model shard folds its own slice. Engines built with an explicit
    2D ``Mesh`` ignore this knob (the mesh itself carries the axis).
    Determinism: the full MESH SHAPE (nodes x model), not just the
    device count, is part of the reproducibility key — see
    docs/scaling.md."""

    SHARD_LAYOUT: str = "auto"
    """Per-leaf model-axis PartitionSpec policy for 2D meshes:
    "auto" (default) = the module's own declared layout
    (zoo ``TransformerLM.spec_layout`` = "transformer": embeddings /
    QKV / FFN sharded per ``tpfl.parallel.mesh.transformer_layout``;
    MLP/CNN/ResNet fall back to "replicated"), or a layout name from
    ``tpfl.parallel.mesh.LAYOUTS`` to force one. "replicated" keeps
    every leaf whole on each device — the model axis then only adds
    redundant compute, so force it only for parity debugging.
    Resolved at engine construction; a cache-key axis of the engine's
    round programs like the other ENGINE_* knobs."""

    SHARD_HOSTS: int = 1
    """Cross-host axis size of the engine's auto mesh
    (``tpfl.parallel.engine.auto_mesh``): 1 (default) = single-process
    meshes only — engine programs lower byte-identical to the
    single-host path; 0 = auto: one ``hosts`` slot per participating
    process (``jax.process_count()`` after
    ``tpfl.parallel.distributed.ensure_distributed``); H > 1 = a
    forced ``hosts`` axis of that size (works single-process too, for
    parity testing — the hosts axis then spans local devices). With
    hosts > 1 the engine lowers a 3D ``hosts x nodes x model`` mesh
    whose FedAvg fold decomposes into two psum legs: per-host node
    shards fold local partials over ``nodes`` (ICI), then the partial
    aggregates cross ``hosts`` over DCN — with ``ENGINE_WIRE_CODEC``
    quantizing that DCN leg natively (see docs/scaling.md "3D mesh &
    cross-host DCN"). A program-cache and ``stamp_contract`` axis like
    the other SHARD_* knobs. Read at engine construction /
    auto_mesh."""

    POPULATION_CLIENTS: int = 0
    """Registered client population of the cross-device tier
    (tpfl.parallel.population.ClientPopulation): 0 (default) = no
    population tier — every logical node is resident, the pure P2P
    layout. N > 0 = N registered, mostly-offline leaf clients attach
    to the engine's resident nodes (now edge aggregators) by per-round
    sampling: each round draws ``POPULATION_SAMPLE`` participants via
    the seeded ``sample_participants`` kernel, broadcasts the current
    edge model with ``broadcast_params``, and folds only the sampled
    cohort — so live state stays O(sampled), never O(N). Registered
    metadata (per-client round counters, last-seen) lives in a NumPy
    structure-of-arrays costing a few bytes/client. A program-cache
    and contract axis of the engine's round programs. See
    docs/scaling.md "Cross-device population tier"."""

    POPULATION_SAMPLE: int = 100
    """Participants sampled per round from the registered population
    (the K of K-out-of-N cross-device FL, pfl-research style): only
    these clients' state is materialized, trained and folded in a
    round; stragglers beyond the engine's quorum/FedBuff cutoffs are
    dropped by the same zero-weight masking as resident nodes. Read
    when a ClientPopulation is built; ignored while
    POPULATION_CLIENTS is 0."""

    SHARD_ROUNDS_PER_DISPATCH: int = 1
    """Federation rounds folded into ONE device dispatch by the
    engine's ``lax.fori_loop`` round window
    (``FederationEngine.run_rounds`` / ``FederationLearner``'s
    local-round loop). Each host dispatch costs a full tunnel RTT
    (~67 ms measured, BENCH_r05 ``dispatch_rtt_ms``) — the same order
    as a whole sim1000 round — so windows of K rounds pay it once per
    K. 1 (default) = one dispatch per round: bit-identical to the
    legacy per-round path, and interrupts (a node told to stop
    mid-fit) are honored at round granularity; larger windows are
    interruptible only between windows."""

    ENGINE_TELEMETRY: bool = False
    """Master gate for the engine plane of the observatory
    (tpfl.management.engine_obs): when on,
    ``FederationEngine.run_rounds`` compiles the TELEMETRY VARIANT of
    its round program — a fixed-shape ``[rounds, ...]`` device buffer
    threaded through the ``fori_loop`` carry that accumulates, per
    round and per node, train loss, update L2 norm, cosine vs the
    round-start reference, global-model delta norm, participation
    count and fold weight mass, all computed from values the program
    already holds (no extra HBM traffic; ``lax.psum`` only where the
    fold already psums). At window close one host-side fan-out replays
    the buffer into the existing planes: per-round ``RoundProfiler``
    rows (PROFILING_ENABLED), ``ConvergenceMonitor``
    divergence/plateau events (LEDGER_ENABLED), ``ContributionLedger``
    entries scored by the same AnomalyScorer/quarantine thresholds as
    the gRPC tier (LEDGER_ENABLED or QUARANTINE_ENABLED), and
    always-on ``tpfl_engine_*`` registry series. Off (default): the
    carry is ELIDED — the engine lowers the byte-identical round
    program of the pre-telemetry path (separate program-cache slot)
    and adds zero work. On, same-seed model outputs stay
    byte-identical at a fixed device count: telemetry is read-only
    over the carry. Read at program-build time (per run_rounds
    call). See docs/observability.md "Engine plane"."""

    ENGINE_WIRE_CODEC: str = "dense"
    """Device-side wire codec for the engine's gossip exchange
    (tpfl.parallel.engine + tpfl.learning.compression): "dense"
    (default), "quant8", "topk", or "topk+quant8". Non-dense lowers
    the PR-1 payload codec INTO the fused round program — each node's
    trained params pass the per-leaf int8-quantize→dequantize (or
    top-k mask) round-trip in-program before the fold's ``lax.psum``,
    so the exchange leg ships int8/sparse tensors over ICI/DCN
    natively (~4x fewer exchange bytes for f32 under quant8) and the
    ENGINE_TELEMETRY carry's ``wire_bytes`` row records bytes/round
    device-side (``tpfl_engine_wire_bytes``). LOSSY like the host-side
    WIRE_CODEC it mirrors (same kernels, same per-leaf policy — the
    bench gates loss parity); "dense" compiles the byte-identical
    pre-codec program (separate program-cache slot, HLO-digest-stable
    across toggles). Entropy coders (zlib/zstd) and delta are host
    byte transforms and are rejected here at knob-read time. Read at
    program-build time (per run_rounds call); the top-k fraction
    rides ``WIRE_TOPK_FRAC``. See docs/scaling.md "Device-side wire
    codecs"."""

    ENGINE_PREFETCH: bool = False
    """Free-running engine windows in ``FederationLearner.fit``
    (tpfl.parallel.window_pipeline): when on, local rounds run through
    the :class:`~tpfl.parallel.window_pipeline.WindowPipeline` — window
    N+1 is dispatched before window N's host leg (telemetry fan-out,
    profiler rows) runs, and the next window's shuffled batch staging
    (``device_put`` placement included) happens on a named background
    prefetch thread, so dispatch RTT and host work overlap device
    compute instead of sitting between windows (the Sebulba split,
    docs/scaling.md "Free-running windows"). PERF-ONLY by
    construction: the device sees the identical program sequence over
    identical buffers, so same-seed fits are byte-identical with the
    knob on or off; interrupts stay window-granular; the prefetch
    thread is joined before fit returns. Off (default): the sequential
    window loop. Read per fit() call."""

    ENGINE_DONATE: bool = True
    """Default donation mode for the engine's round program
    (``FederationEngine.run_rounds(donate=None)``): True donates the
    state buffers (params, SCAFFOLD variates, aux) to the dispatch —
    XLA writes the fold's outputs INTO the input buffers, so a window
    costs no staging copy of the model state and peak HBM stays
    one-model-deep (verify with ``FederationEngine.donation_report``;
    the engine_wire bench tier gates donation-clean HLO and
    byte-identical outputs vs the non-donating variant). The handed-in
    buffers are CONSUMED — callers that re-feed the same arrays
    (repeated-call benchmarking) pass ``donate=False`` explicitly or
    rebind from the outputs (``profiling.best_of_wall_donated``).
    False: every dispatch allocates fresh outputs (debugging aid)."""

    ELASTIC_CAPACITY_MIN: int = 2
    """Floor of the elastic engine's pow-2 capacity tiers
    (tpfl.parallel.membership.MembershipView /
    tpfl.parallel.mesh.capacity_tier): the engine compiles its round
    programs at the smallest power-of-two ≥ max(live members, this
    floor), so joins/leaves/crashes/quarantine evictions inside a tier
    are pure weight-mask edits with ZERO recompiles — only crossing a
    tier boundary lowers a new program (and returning to a seen tier
    is a cache hit; the capacity is a program-cache key axis). A
    higher floor trades padded rows (wasted device work) for headroom
    before the first promotion. Read when a MembershipView is built.
    See docs/deployment.md "Elastic membership & preemption"."""

    COMPILE_CACHE_DIR: str = ""
    """Directory for JAX's persistent compilation cache, wired into
    the engine's program cache (tpfl.management.profiling
    .ensure_compile_cache, called at FederationEngine construction):
    when set, every XLA executable the engine lowers is written to
    disk, and a restarted/preempted process RELOADS it instead of
    recompiling — cold-start cost after kill-and-resume drops to cache
    I/O. The observatory counts the reloads in the always-on
    ``tpfl_compile_cache_warm_total`` counter so cold-start cost is
    measurable in production. "" (default) leaves JAX's cache
    configuration untouched. Read at engine construction."""

    CHECKPOINT_DIR: str = ""
    """Directory for engine-state checkpoints
    (tpfl.management.checkpoint.EngineCheckpointer): when set,
    ``FederationLearner.fit`` snapshots the engine federation state —
    params/variates/aux as UNPADDED host rows (mesh-agnostic: a
    checkpoint written on a 1×1 mesh restores onto 4×2 and back),
    plus the FedBuff schedule position, AsyncController trajectory,
    quarantine/probation state, membership slots and RNG seed — every
    ``CHECKPOINT_EVERY_WINDOWS`` windows, atomically
    pointer-published (the same LATEST discipline as node
    checkpoints). "" (default): no engine checkpointing. Read per
    fit() call."""

    CHECKPOINT_EVERY_WINDOWS: int = 0
    """Snapshot cadence for CHECKPOINT_DIR, in engine windows: every
    K-th window's output state is copied device→host OFF the critical
    path (the snapshot rides the window pipeline's
    ``copy_to_host_async`` host leg, landing while the next window's
    device work runs) and written as a checkpoint. 0 (default)
    disables cadence snapshots even when CHECKPOINT_DIR is set (the
    SIGTERM path below can still emit a final checkpoint). The bench
    ``elastic`` tier gates the cadence overhead inside a 5% budget.
    Read per fit() call."""

    CHECKPOINT_ON_SIGTERM: bool = False
    """Preemption hardening: when on (and CHECKPOINT_DIR is set),
    ``FederationLearner.fit`` installs a SIGTERM handler
    (tpfl.management.checkpoint.install_sigterm_checkpoint) that
    drains the flight recorder and emits a final checkpoint of the
    last completed snapshot before chaining the previous handler — a
    preempted host resumes mid-experiment instead of losing the run.
    Main-thread only (the signal module's rule); the handler is
    removed when fit returns. Off by default: shutdown paths stay
    exactly the PR-16 behavior. Read per fit() call."""

    # --- concurrency diagnostics ---
    TRACE_CONTRACTS: bool = False
    """Opt-in runtime trace-contract checking (tpfl.concurrency): every
    compiled program the federation engine caches is stamped with the
    Settings-knob values its cache key was built from
    (``ENGINE_TELEMETRY`` / ``ENGINE_WIRE_CODEC`` / ``WIRE_TOPK_FRAC``
    / ``ENGINE_DONATE``), and every dispatch re-checks the stamp
    against the live resolved values — a mismatch means a cache key
    lost an axis and a STALE compiled program was about to run;
    ``TraceContractError`` names the offending knob and both values.
    The runtime half of ``tools/tpflcheck``'s capture pass (the static
    half proves key totality at review time; this catches what static
    analysis cannot — indirection through dynamic dispatch). Read at
    program BUILD time like ``LOCK_TRACING``; off by default (zero
    wrappers, zero per-dispatch reads)."""

    STATE_CONTRACTS: bool = False
    """Opt-in checkpoint self-verification
    (``tpfl.management.checkpoint``): every ``EngineCheckpointer.save``
    immediately re-loads its own serialized snapshot onto a shadow
    import and compares per-key digests against the live state dict —
    a key that does not survive the serialize/restore round-trip (or
    changes bytes doing so) raises ``StateContractError`` naming the
    field, BEFORE the snapshot is published as LATEST. The runtime
    half of ``tools/tpflcheck``'s state pass (the static half proves
    export/import totality at review time; this catches value-level
    loss static analysis cannot see). Read per save; off by default
    (zero extra serialization work)."""

    RANK_CONTRACTS: bool = False
    """Opt-in multi-host dispatch receipts (``tpfl.parallel.ranksafe``):
    every engine window dispatch appends the digest of its program
    cache key + lowered-HLO fingerprint to an ordered per-process log,
    and ``crosshost.launch`` compares the receipts across ranks —
    divergence fails with the first (rank, ordinal, key) witness
    instead of hanging the fleet on DCN. The runtime half of
    ``tools/tpflcheck``'s rank pass (the static half proves no
    dispatch is rank-gated at review time; receipts catch
    data-dependent divergence). Read per dispatch; off by default
    (zero recording, zero extra traces)."""

    LOCK_TRACING: bool = False
    """Opt-in runtime lock-order tracing (tpfl.concurrency): every lock
    built through ``make_lock`` becomes a ``TracedLock`` that records
    the acquisition graph (lock A held while acquiring lock B ⇒ edge
    A→B, witnessed by the acquiring thread's name), and ``Node.stop``
    asserts the graph is acyclic — a cycle is a latent deadlock, and
    the error carries the witness chain. Read at lock CREATION time, so
    it must be set before nodes are built. Off by default (one
    thread-local append per acquire, measured <10% round-throughput
    overhead in bench.py's analysis tier — fine for chaos/e2e runs,
    not for 1000-node profiles). The static half of the same invariant
    runs in CI via ``python -m tools.tpflcheck`` (docs/concurrency.md)."""

    # --- determinism / TPU ---
    SEED: int | None = None
    """Global seed for reproducible experiments (fork feature)."""

    DEFAULT_DTYPE: str = "float32"
    """Parameter dtype; compute may run bfloat16 on TPU."""

    EXACT_AGGREGATION: bool = True
    """When all train-set nodes share one process/mesh, replace
    gossip-until-converged with an exact on-device mean (see
    tpfl.parallel). Cross-host gossip still applies between processes."""

    @classmethod
    def set_test_settings(cls) -> None:
        """Aggressive timings for tests — parity with utils/utils.py:39-57."""
        # Profile totality (enforced by tools/tpflcheck's knob lint):
        # every knob any profile tunes is assigned in ALL profiles, so
        # switching profiles mid-process can never leak a value from
        # the previous one (set_scale_settings leaving
        # AGGREGATION_STALL armed inside a later test run was exactly
        # this bug class).
        cls.GRPC_TIMEOUT = 0.5
        cls.HEARTBEAT_PERIOD = 0.5
        cls.HEARTBEAT_TIMEOUT = 2.0
        cls.ELECTION = "vote"
        cls.GOSSIP_PERIOD = 0.0
        cls.TTL = 10
        cls.GOSSIP_MESSAGES_PER_PERIOD = 100
        cls.AMOUNT_LAST_MESSAGES_SAVED = 100
        cls.GOSSIP_MODELS_PERIOD = 0.1
        cls.GOSSIP_MODELS_PER_ROUND = 4
        cls.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = 10
        cls.TRAIN_SET_SIZE = 4
        cls.SIM_BATCH_WINDOW = 0.05
        cls.VOTE_TIMEOUT = 30.0
        cls.AGGREGATION_TIMEOUT = 30.0
        # Reference behavior: wait the full timeout, close only on full
        # coverage; fast early-stop polling suits short test rounds.
        cls.AGGREGATION_STALL = None
        cls.ROUND_WAIT_POLL = 0.1
        cls.WAIT_HEARTBEATS_CONVERGENCE = 0.2
        cls.GOSSIP_METRICS = True
        cls.LOG_LEVEL = "DEBUG"
        cls.ASYNC_LOGGER = False
        cls.FILE_LOGGER = False
        cls.LOCK_TRACING = False
        cls.TRACE_CONTRACTS = False
        # Contracts ON in tests: every checkpoint save shadow-verifies
        # its own round-trip and every engine dispatch logs its
        # program digest — the suite exercises both runtime halves
        # continuously, so a totality regression fails loudly here
        # before it ever reaches a fleet.
        cls.STATE_CONTRACTS = True
        cls.RANK_CONTRACTS = True
        # Exactness first in tests: dense payloads (v3 zero-copy layout
        # — still exact), no residual gossip; codec tests opt in
        # explicitly. Zero-copy stays byte-path (INPROC_ZERO_COPY off)
        # and aggregation folds in canonical order at round close
        # (AGG_STREAM_EAGER off) so seeded runs are bit-reproducible;
        # the zero-copy/eager tests toggle both per-case.
        cls.WIRE_CODEC = "dense"
        cls.WIRE_DELTA = False
        cls.WIRE_FORMAT = 3
        cls.WIRE_CHUNK_SIZE = 256 * 1024
        cls.INPROC_ZERO_COPY = False
        cls.AGG_STREAM_EAGER = False
        cls.AGG_MEDIAN_RESERVOIR = 64
        cls.BUFFER_POOL_BUFFERS = 8
        cls.BUFFER_POOL_MAX_BYTES = 256 * 1024 * 1024
        # Fault tolerance: short backoffs (tests run against loopback),
        # fast half-open probes; quorum at reference behavior — chaos
        # tests override per-case.
        cls.RETRY_MAX_ATTEMPTS = 2
        cls.RETRY_BASE_DELAY = 0.05
        cls.RETRY_MAX_DELAY = 0.25
        cls.BREAKER_THRESHOLD = 3
        cls.BREAKER_PROBE_PERIOD = 1.0
        cls.ROUND_QUORUM = 1.0
        # Async rounds off by default (reference-parity sync lifecycle);
        # async tests/bench toggle per-case. Serialized discipline ON
        # for this profile: deferred canonical folds (schedule order
        # when one is attached) keep seeded async runs byte-identical.
        cls.ASYNC_ROUNDS = False
        cls.ASYNC_BUFFER_K = 4
        cls.ASYNC_STALENESS_EXP = 0.5
        cls.ASYNC_ROUND_DEADLINE = 15.0
        cls.ASYNC_SERIALIZED = True
        # Adaptive control off in tests (static PR-10 knobs = reference
        # behavior); controller tests toggle per-case. Untagged
        # contributions stay fresh for parity with pre-async peers.
        cls.ASYNC_ADAPTIVE = False
        cls.ASYNC_K_MIN = 2
        cls.ASYNC_K_MAX = 16
        cls.ASYNC_CTL_EWMA = 0.3
        cls.ASYNC_CTL_QUANTILE = 0.9
        cls.ASYNC_STALENESS_MAX = 16
        cls.ASYNC_UNTAGGED_POLICY = "fresh"
        # Telemetry off in tests by default: tracing tests toggle
        # per-case; the registry records regardless (it is cheap and
        # deterministic).
        cls.TELEMETRY_ENABLED = False
        cls.TELEMETRY_RING = 512
        cls.TELEMETRY_MAX_LABELSETS = 64
        cls.TELEMETRY_DUMP_DIR = ""
        cls.METRIC_MAX_POINTS = 4096
        # Fleet observatory off in tests by default: fleetobs tests
        # arm the publisher/watchdog per-case with explicit dirs,
        # targets and (deterministic) evaluation timestamps.
        cls.FLEETOBS_SNAPSHOT_PERIOD = 0.0
        cls.FLEETOBS_DIR = ""
        cls.SLO_TARGETS = ""
        cls.SLO_EWMA = 0.3
        cls.SLO_BREACH_WINDOWS = 2
        # Device-plane profiling off by default (profiling tests and
        # the bench profiling tier toggle per-case); a low storm
        # threshold would misfire on tests that legitimately churn
        # shapes, so the class default rides.
        cls.PROFILING_ENABLED = False
        cls.PROFILING_RECOMPILE_WARN = 8
        cls.PROFILING_TRACE_DIR = ""
        # Learning-plane ledger off by default (ledger tests and the
        # bench ledger tier toggle per-case) — disabled taps add zero
        # device dispatches, keeping seeded runs bit-identical to
        # pre-ledger behavior.
        cls.LEDGER_ENABLED = False
        cls.LEDGER_RING = 1024
        cls.LEDGER_ANOMALY_Z = 6.0
        cls.LEDGER_ANOMALY_COS = 0.0
        cls.LEDGER_ANOMALY_MIN_N = 4
        cls.LEDGER_CONVERGENCE_WINDOW = 5
        # Active defense off by default (quarantine/robust tests and the
        # bench byzantine tier toggle per-case) — verdicts change what
        # aggregates, so seeded reference-parity runs keep it off.
        cls.QUARANTINE_ENABLED = False
        cls.QUARANTINE_PROBATION_ROUNDS = 2
        cls.AGG_ROBUST_BUFFER = 64
        cls.ATTACK_NOISE_STD = 0.1
        # Node-axis sharding off in tests: the suite's 8 virtual CPU
        # devices share one host's cores, and single-dispatch rounds
        # keep seeded runs bit-identical to the reference path. The
        # engine tests opt in per-case with explicit meshes/windows.
        cls.SHARD_NODES = False
        cls.SHARD_DEVICES = 0
        cls.SHARD_MODEL = 1
        cls.SHARD_LAYOUT = "auto"
        # Single-process meshes and no population tier in tests —
        # cross-host / cross-device cases force SHARD_HOSTS /
        # POPULATION_CLIENTS per-case.
        cls.SHARD_HOSTS = 1
        cls.POPULATION_CLIENTS = 0
        cls.POPULATION_SAMPLE = 100
        cls.SHARD_ROUNDS_PER_DISPATCH = 1
        # Engine-plane telemetry off by default (engine_obs tests and
        # the bench engine_obs tier toggle per-case): the elided carry
        # keeps the engine's round program byte-identical to the
        # reference path.
        cls.ENGINE_TELEMETRY = False
        # Exactness first in tests (the WIRE_CODEC rule above applies
        # on-device too): dense in-program exchange; codec tests opt in
        # per-case. Donation stays on — it is the production path and
        # never changes numerics (the engine_wire tests pin byte
        # identity vs donate=False).
        cls.ENGINE_WIRE_CODEC = "dense"
        cls.ENGINE_DONATE = True
        # Sequential windows by default in tests — the pipelined path
        # is byte-identical (test_engine_async pins it) but interleaves
        # host work, which single-stepping tests don't want.
        cls.ENGINE_PREFETCH = False
        # Elastic/preemption machinery off by default in tests: fixed
        # membership and no disk traffic keep seeded runs hermetic;
        # the elastic tests opt in per-case with explicit views/dirs.
        cls.ELASTIC_CAPACITY_MIN = 2
        cls.COMPILE_CACHE_DIR = ""
        cls.CHECKPOINT_DIR = ""
        cls.CHECKPOINT_EVERY_WINDOWS = 0
        cls.CHECKPOINT_ON_SIGTERM = False

    @classmethod
    def set_standalone_settings(cls) -> None:
        """Single-host many-node simulation profile — parity with
        examples/mnist.py:43-70."""
        cls.GRPC_TIMEOUT = 2.0
        cls.HEARTBEAT_PERIOD = 10.0
        cls.HEARTBEAT_TIMEOUT = 45.0
        cls.ELECTION = "vote"
        cls.GOSSIP_PERIOD = 1.0
        cls.TTL = 40
        cls.GOSSIP_MESSAGES_PER_PERIOD = 9999999
        cls.AMOUNT_LAST_MESSAGES_SAVED = 9999999
        cls.GOSSIP_MODELS_PERIOD = 1.0
        cls.GOSSIP_MODELS_PER_ROUND = 4
        cls.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = 30
        cls.TRAIN_SET_SIZE = 4
        cls.SIM_BATCH_WINDOW = 0.2
        cls.VOTE_TIMEOUT = 1200.0
        cls.AGGREGATION_TIMEOUT = 1200.0
        cls.AGGREGATION_STALL = None
        cls.ROUND_WAIT_POLL = 0.5
        cls.WAIT_HEARTBEATS_CONVERGENCE = 4.0
        cls.GOSSIP_METRICS = True
        cls.LOG_LEVEL = "INFO"
        cls.ASYNC_LOGGER = True
        cls.FILE_LOGGER = True
        cls.WIRE_CHUNK_SIZE = 256 * 1024
        cls.LOCK_TRACING = False
        cls.TRACE_CONTRACTS = False
        cls.STATE_CONTRACTS = False
        cls.RANK_CONTRACTS = False
        # Single-host, handful of nodes: bytes are not the bottleneck —
        # keep the exact dense wire (reference-parity behavior; the v3
        # layout is exact, only the framing differs). By-reference
        # handoff and eager accumulation stay off: reference parity
        # over speed in this profile, and close-time sorted folds keep
        # seeded runs bit-reproducible.
        cls.WIRE_CODEC = "dense"
        cls.WIRE_DELTA = False
        cls.WIRE_FORMAT = 3
        cls.INPROC_ZERO_COPY = False
        cls.AGG_STREAM_EAGER = False
        cls.AGG_MEDIAN_RESERVOIR = 64
        cls.BUFFER_POOL_BUFFERS = 8
        cls.BUFFER_POOL_MAX_BYTES = 256 * 1024 * 1024
        # Fault tolerance: patient backoffs matching the long protocol
        # timeouts; quorum at reference behavior.
        cls.RETRY_MAX_ATTEMPTS = 3
        cls.RETRY_BASE_DELAY = 0.2
        cls.RETRY_MAX_DELAY = 2.0
        cls.BREAKER_THRESHOLD = 3
        cls.BREAKER_PROBE_PERIOD = 15.0
        cls.ROUND_QUORUM = 1.0
        # Async rounds opt-in here too; the patient deadline matches
        # this profile's long protocol timeouts, and the serialized
        # discipline keeps seeded runs reproducible.
        cls.ASYNC_ROUNDS = False
        cls.ASYNC_BUFFER_K = 4
        cls.ASYNC_STALENESS_EXP = 0.5
        cls.ASYNC_ROUND_DEADLINE = 120.0
        cls.ASYNC_SERIALIZED = True
        # Adaptive control is an opt-in diagnostic here (like tracing):
        # a handful of nodes on one host rarely needs tuned knobs, and
        # static knobs keep seeded runs reference-comparable.
        cls.ASYNC_ADAPTIVE = False
        cls.ASYNC_K_MIN = 2
        cls.ASYNC_K_MAX = 16
        cls.ASYNC_CTL_EWMA = 0.3
        cls.ASYNC_CTL_QUANTILE = 0.9
        cls.ASYNC_STALENESS_MAX = 16
        cls.ASYNC_UNTAGGED_POLICY = "fresh"
        # Tracing is an opt-in diagnostic (enable for a run you intend
        # to traceview); the ring and caps stay at class defaults.
        cls.TELEMETRY_ENABLED = False
        cls.TELEMETRY_RING = 512
        cls.TELEMETRY_MAX_LABELSETS = 64
        cls.TELEMETRY_DUMP_DIR = ""
        cls.METRIC_MAX_POINTS = 4096
        # Fleet observatory: an interactive single host IS its own
        # fleet — no periodic snapshot publisher, no standing SLOs;
        # point FLEETOBS_DIR/SLO_TARGETS at an experiment explicitly.
        cls.FLEETOBS_SNAPSHOT_PERIOD = 0.0
        cls.FLEETOBS_DIR = ""
        cls.SLO_TARGETS = ""
        cls.SLO_EWMA = 0.3
        cls.SLO_BREACH_WINDOWS = 2
        # Profiling is an opt-in diagnostic here, like tracing: enable
        # it (or pass the CLI's --profile) for a run you intend to
        # read attribution/traces from.
        cls.PROFILING_ENABLED = False
        cls.PROFILING_RECOMPILE_WARN = 8
        cls.PROFILING_TRACE_DIR = ""
        # Ledger is an opt-in diagnostic here too — enable it for runs
        # whose per-peer contribution stats / anomaly flags you intend
        # to read (traceview --ledger).
        cls.LEDGER_ENABLED = False
        cls.LEDGER_RING = 1024
        cls.LEDGER_ANOMALY_Z = 6.0
        cls.LEDGER_ANOMALY_COS = 0.0
        cls.LEDGER_ANOMALY_MIN_N = 4
        cls.LEDGER_CONVERGENCE_WINDOW = 5
        # Active defense is opt-in here too: enable QUARANTINE_ENABLED
        # (with the ledger) for runs expected to contain adversaries.
        cls.QUARANTINE_ENABLED = False
        cls.QUARANTINE_PROBATION_ROUNDS = 2
        cls.AGG_ROBUST_BUFFER = 64
        cls.ATTACK_NOISE_STD = 0.1
        # Single-host handful-of-nodes parity profile: one device, one
        # dispatch per round (reference behavior first).
        cls.SHARD_NODES = False
        cls.SHARD_DEVICES = 0
        cls.SHARD_MODEL = 1
        cls.SHARD_LAYOUT = "auto"
        # One process, resident nodes only: no cross-host axis, no
        # cross-device population — the reference P2P layout.
        cls.SHARD_HOSTS = 1
        cls.POPULATION_CLIENTS = 0
        cls.POPULATION_SAMPLE = 100
        cls.SHARD_ROUNDS_PER_DISPATCH = 1
        # Engine telemetry is an opt-in diagnostic here, like tracing/
        # profiling: enable it for engine-window runs you intend to
        # read attribution / convergence / ledger verdicts from.
        cls.ENGINE_TELEMETRY = False
        # Reference parity over bytes on a single host: the exchange
        # stays exact-dense in-program, and donation (numerics-free)
        # stays on.
        cls.ENGINE_WIRE_CODEC = "dense"
        cls.ENGINE_DONATE = True
        # Interactive single-host runs: the free-running driver only
        # helps once windows carry real work; opt in per-experiment.
        cls.ENGINE_PREFETCH = False
        # Elastic/preemption machinery opt-in here like the other ops
        # knobs: point CHECKPOINT_DIR/COMPILE_CACHE_DIR at durable
        # paths for runs you intend to preempt and resume.
        cls.ELASTIC_CAPACITY_MIN = 2
        cls.COMPILE_CACHE_DIR = ""
        cls.CHECKPOINT_DIR = ""
        cls.CHECKPOINT_EVERY_WINDOWS = 0
        cls.CHECKPOINT_ON_SIGTERM = False

    @classmethod
    def set_scale_settings(cls) -> None:
        """Single-host simulation at 100+ nodes: message throttles and
        protocol timeouts sized so control floods and model diffusion
        scale with the node count (the test/standalone profiles assume
        single-digit federations)."""
        # O(N²) vote flooding is the measured scale killer (500-node
        # vote runs take ~6x longer than hash-election runs on one
        # host); deterministic sortition is the profile default. The
        # GLOBAL default stays "vote" for reference parity.
        cls.ELECTION = "hash"
        # Knobs this profile never tuned are pinned at their class
        # defaults (profile totality — see set_test_settings).
        cls.GRPC_TIMEOUT = 10.0
        cls.GOSSIP_PERIOD = 0.0
        cls.TTL = 10
        cls.GOSSIP_MESSAGES_PER_PERIOD = 100_000
        cls.AMOUNT_LAST_MESSAGES_SAVED = 100_000
        # 0.25 s (not 0.05): every push tick's delivery runs the
        # receiver's decode + jitted add_model in the sender's thread;
        # at 0.05 s the 10 trainers' mutual exchange re-pushed
        # payloads ~20x/s each and the redundant deliveries serialized
        # on the GIL + device dispatch for minutes (measured at 1000
        # nodes: 6 min to exchange 10 partials).
        cls.GOSSIP_MODELS_PERIOD = 0.25
        cls.GOSSIP_MODELS_PER_ROUND = 20
        cls.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = 20
        # Safety net, not the normal exit: with coverage announcements
        # going DIRECTLY to train-set peers the exchange completes
        # coverage in seconds; the stall fires only when an elected
        # peer genuinely never delivers. 60 s keeps slow-but-alive
        # peers in (a 30 s stall measurably fractured the aggregate
        # when it fired mid-exchange under flood-lagged coverage).
        cls.AGGREGATION_STALL = 60.0
        # Heartbeats TTL-flood through relay hubs: at N nodes each beat
        # costs O(N) relays, so the beat rate — not the timeout — sets
        # the hub's floor load. 10s matches the standalone profile.
        cls.HEARTBEAT_PERIOD = 10.0
        cls.HEARTBEAT_TIMEOUT = 45.0
        cls.TRAIN_SET_SIZE = 4
        cls.SIM_BATCH_WINDOW = 0.2
        cls.VOTE_TIMEOUT = 120.0
        cls.AGGREGATION_TIMEOUT = 120.0
        cls.WAIT_HEARTBEATS_CONVERGENCE = 0.5
        cls.LOG_LEVEL = "INFO"
        cls.ASYNC_LOGGER = False
        cls.FILE_LOGGER = False
        cls.GOSSIP_METRICS = False
        cls.WIRE_CHUNK_SIZE = 256 * 1024
        cls.LOCK_TRACING = False
        cls.TRACE_CONTRACTS = False
        # Scale keeps both contract verifiers OFF: the shadow re-import
        # doubles checkpoint serialization work and the dispatch
        # receipts add a trace per cache key — diagnostics a production
        # fleet arms selectively, not a standing tax.
        cls.STATE_CONTRACTS = False
        cls.RANK_CONTRACTS = False
        # Hundreds of round-result waiters waking 2x/s each is a
        # standing GIL tax on the trainers forming the aggregate they
        # wait for; the event still wakes them INSTANTLY on FullModel
        # arrival — this bounds only early-stop detection latency.
        cls.ROUND_WAIT_POLL = 2.0
        # The 1000-node runs are gossip-bound, not compute-bound:
        # quantize + DEFLATE the weight payloads (~4-5x fewer bytes at
        # convergence within noise — bench.py's seeded A/B) and ship
        # round results as residuals against the previous round's
        # aggregate wherever the peer acknowledged holding it.
        cls.WIRE_CODEC = "quant8+zlib"
        cls.WIRE_DELTA = True
        cls.WIRE_FORMAT = 3
        # 1000 co-located nodes share one address space: hand model
        # payloads across by reference (no encode/decode/memcpy per
        # hop) and fold contributions into the on-device accumulator
        # as they arrive — together these make the round memcpy-free
        # between fit and finalize. (Dense fallback payloads that DO
        # encode — codec nacks, gRPC peers — stage through the
        # per-node BufferPool instead of allocating per tick.)
        cls.INPROC_ZERO_COPY = True
        cls.AGG_STREAM_EAGER = True
        cls.AGG_MEDIAN_RESERVOIR = 64
        cls.BUFFER_POOL_BUFFERS = 8
        cls.BUFFER_POOL_MAX_BYTES = 256 * 1024 * 1024
        # Fault tolerance: only one retry — backoff sleeps run on
        # contended sender threads (gossiper/heartbeater share the GIL
        # with 1000 in-process nodes), and the breaker caps what a dead
        # hub can cost regardless. Quorum stays 1.0: the stall exit
        # (AGGREGATION_STALL above) already handles absent peers and —
        # unlike an eager quorum — waits for intake to go quiet first.
        cls.RETRY_MAX_ATTEMPTS = 2
        cls.RETRY_BASE_DELAY = 0.1
        cls.RETRY_MAX_DELAY = 1.0
        cls.BREAKER_THRESHOLD = 3
        cls.BREAKER_PROBE_PERIOD = 30.0
        cls.ROUND_QUORUM = 1.0
        # Async rounds are opt-in even at scale (the sync lifecycle is
        # the measured-baseline path), but when enabled this profile
        # runs truly FREE-RUNNING: eager arrival-order folds, a wider
        # buffer for the bigger fleets, and a deadline sized to the
        # stall-window delivery bound (AGGREGATION_STALL's sizing rule
        # applies to it unchanged).
        cls.ASYNC_ROUNDS = False
        cls.ASYNC_BUFFER_K = 8
        cls.ASYNC_STALENESS_EXP = 0.5
        cls.ASYNC_ROUND_DEADLINE = 60.0
        cls.ASYNC_SERIALIZED = False
        # Free-running fleets are what the adaptive controller is FOR:
        # the static K/deadline that fit a 10-node bench fleet starve
        # or barrier a 1000-node one, so when async is enabled at scale
        # the knobs tune themselves from the observed arrival cadence.
        # Untagged contributions fold at the maximum discount — at this
        # scale an untagged (or tag-stripping) minority must not carry
        # full-weight mass into every buffer.
        cls.ASYNC_ADAPTIVE = True
        cls.ASYNC_K_MIN = 2
        cls.ASYNC_K_MAX = 32
        cls.ASYNC_CTL_EWMA = 0.3
        cls.ASYNC_CTL_QUANTILE = 0.9
        cls.ASYNC_STALENESS_MAX = 16
        cls.ASYNC_UNTAGGED_POLICY = "max-stale"
        # At 1000 in-process nodes every span append shares the GIL
        # with the federation itself: tracing stays off (the <5%
        # measured overhead is per-node, not per-host), the ring
        # shrinks (1000 rings x 512 spans is real memory), and the
        # label cap guards against per-peer label explosions.
        cls.TELEMETRY_ENABLED = False
        cls.TELEMETRY_RING = 128
        cls.TELEMETRY_MAX_LABELSETS = 64
        cls.TELEMETRY_DUMP_DIR = ""
        cls.METRIC_MAX_POINTS = 4096
        # Scale is what the fleet plane is FOR, but the publisher
        # still needs an operator-provided shared dir (a deployment
        # decision, like CHECKPOINT_DIR): a 30 s cadence costs one
        # registry fold + one small JSON write per period once armed.
        # SLOs are per-deployment numbers — no universal default.
        cls.FLEETOBS_SNAPSHOT_PERIOD = 30.0
        cls.FLEETOBS_DIR = ""
        cls.SLO_TARGETS = ""
        cls.SLO_EWMA = 0.3
        cls.SLO_BREACH_WINDOWS = 2
        # 1000 in-process nodes: per-call signature probes and round
        # spans share the GIL with the federation — profiling stays an
        # explicit opt-in, and a higher storm threshold tolerates the
        # wider legitimate shape variety (many partition sizes).
        cls.PROFILING_ENABLED = False
        cls.PROFILING_RECOMPILE_WARN = 16
        cls.PROFILING_TRACE_DIR = ""
        # Ledger off at 1000 in-process nodes for the same GIL/ring-
        # memory reasons as tracing; the ring shrinks when enabled
        # ad hoc (1000 rings x 1024 entries is real memory).
        cls.LEDGER_ENABLED = False
        cls.LEDGER_RING = 256
        cls.LEDGER_ANOMALY_Z = 6.0
        cls.LEDGER_ANOMALY_COS = 0.0
        cls.LEDGER_ANOMALY_MIN_N = 4
        cls.LEDGER_CONVERGENCE_WINDOW = 5
        # At 1000 in-process nodes the live-scoring dispatch per intake
        # shares the one device queue with the vmapped fits — active
        # defense stays an explicit opt-in at this profile's scale.
        cls.QUARANTINE_ENABLED = False
        cls.QUARANTINE_PROBATION_ROUNDS = 2
        cls.AGG_ROBUST_BUFFER = 64
        cls.ATTACK_NOISE_STD = 0.1
        # Scale is where the pod-scale engine earns its keep: spread
        # the node axis over every visible chip (no-op on one device)
        # and fold 8 rounds into each dispatch — at ~67 ms tunnel RTT
        # and ~3 ms/round for the sim1000 shape, per-round dispatch is
        # the dominant wall term the window removes. Trade-off: fit
        # interrupts land between windows, and the arrival-order
        # eager-fold caveat (AGG_STREAM_EAGER above) applies to
        # cross-window reproducibility the same way.
        cls.SHARD_NODES = True
        cls.SHARD_DEVICES = 0
        # Model axis off by default even at scale: the zoo's bench
        # models fit one chip, and nodes-axis throughput is the
        # scale profile's first-order win. Raise SHARD_MODEL (a
        # divisor of the device count) to federate models bigger
        # than one chip's HBM; the layout then comes from the module
        # ("auto" = zoo transformer rules, MLP/CNN replicated).
        cls.SHARD_MODEL = 1
        cls.SHARD_LAYOUT = "auto"
        # Auto cross-host: a process launched under
        # jax.distributed (tpfl.parallel.distributed) contributes one
        # hosts-axis slot per participating process; a lone process
        # resolves to hosts=1 and lowers the single-host programs
        # unchanged. Population tier stays opt-in even at scale — set
        # POPULATION_CLIENTS to the registered census to turn the
        # resident nodes into edge aggregators sampling
        # POPULATION_SAMPLE leaf clients per round.
        cls.SHARD_HOSTS = 0
        cls.POPULATION_CLIENTS = 0
        cls.POPULATION_SAMPLE = 100
        cls.SHARD_ROUNDS_PER_DISPATCH = 8
        # At scale the engine IS the federation — without the carry an
        # 8-round window is one opaque dispatch none of the planes can
        # see into — but the fan-out's host work is per-node-per-round,
        # so like the other observability knobs it stays an explicit
        # opt-in at this profile's node counts.
        cls.ENGINE_TELEMETRY = False
        # The scale profile already ships quant8 on the host wire
        # (WIRE_CODEC above) — the in-program exchange follows suit:
        # cross-host/sharded gossip psums int8-round-tripped tensors
        # natively (~4x fewer exchange bytes at the bench-gated loss
        # parity). Donation on: O(1)-model HBM per window.
        cls.ENGINE_WIRE_CODEC = "quant8"
        cls.ENGINE_DONATE = True
        # 8-round windows carry enough device work to hide the host
        # legs behind — free-running is the point of this profile:
        # dispatch RTT, telemetry fan-out and batch staging all
        # overlap device compute (byte-identical either way).
        cls.ENGINE_PREFETCH = True
        # Long-running fleets resize and get preempted — the scale
        # profile keeps the elastic floor at 2 (first promotion cheap)
        # and SIGTERM hardening ON so a preempted host leaves a final
        # checkpoint; the dirs stay empty (operator-provided paths —
        # durable storage is a deployment decision, not a profile's).
        cls.ELASTIC_CAPACITY_MIN = 2
        cls.COMPILE_CACHE_DIR = ""
        cls.CHECKPOINT_DIR = ""
        cls.CHECKPOINT_EVERY_WINDOWS = 0
        cls.CHECKPOINT_ON_SIGTERM = True

    @classmethod
    def snapshot(cls) -> dict[str, Any]:
        """Capture all settings (for restoring after tests)."""
        return {
            k: getattr(cls, k)
            for k in dir(cls)
            if k.isupper() and not k.startswith("_")
        }

    @classmethod
    def restore(cls, snap: dict[str, Any]) -> None:
        for k, v in snap.items():
            setattr(cls, k, v)

    @classmethod
    def from_env(cls) -> None:
        """Override any setting from a ``TPFL_<NAME>`` environment variable."""
        for k in list(cls.snapshot()):
            env = os.environ.get(f"TPFL_{k}")
            if env is None:
                continue
            cur = getattr(cls, k)
            if isinstance(cur, bool):
                setattr(cls, k, env.lower() in ("1", "true", "yes"))
            elif isinstance(cur, int):
                setattr(cls, k, int(env))
            elif isinstance(cur, float):
                setattr(cls, k, float(env))
            elif cur is None:
                # None-default settings (e.g. SEED): parse numerically when
                # possible so TPFL_SEED=42 yields an int, not a string.
                for parse in (int, float):
                    try:
                        setattr(cls, k, parse(env))
                        break
                    except ValueError:
                        continue
                else:
                    setattr(cls, k, env)
            else:
                setattr(cls, k, env)
