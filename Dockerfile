# tpfl deployment image (parity: the reference ships /Dockerfile).
#
# Two build modes, selected by BASE:
#   CPU (default, works anywhere — CI, protocol-only hubs, tests):
#     docker build -t tpfl .
#   TPU VM (run on a Cloud TPU VM so /dev devices are present; the
#   libtpu wheel rides the jax[tpu] extra):
#     docker build -t tpfl --build-arg JAX_EXTRA="jax[tpu]" \
#       --build-arg PIP_EXTRA_INDEX="-f https://storage.googleapis.com/jax-releases/libtpu_releases.html" .
#
# A container is ONE protocol participant (one gRPC port). Multislice
# deployment = one container per host/slice running
# `python -m tpfl.examples.multislice` (see docs/deployment.md).

FROM python:3.12-slim

ARG JAX_EXTRA="jax"
ARG PIP_EXTRA_INDEX=""

WORKDIR /app

ENV PYTHONUNBUFFERED=1 \
    PIP_DISABLE_PIP_VERSION_CHECK=on \
    PIP_DEFAULT_TIMEOUT=100

COPY pyproject.toml README.md ./
COPY tpfl ./tpfl

RUN pip install --no-cache-dir ${PIP_EXTRA_INDEX} "${JAX_EXTRA}" \
    && pip install --no-cache-dir .

# gRPC default port for the quickstart examples; override at run time.
EXPOSE 6666

# Passive node by default — join it from a peer (node2/multislice) or
# exec the CLI: `docker run tpfl tpfl experiment list`. Binds 0.0.0.0
# so Docker's published port actually reaches the server.
CMD ["python", "-m", "tpfl.examples.node1", "--port", "6666", "--host", "0.0.0.0"]
