"""Drive: free-running engine (PR 16) — WindowPipeline + FedBuffSchedule.

Run from the repo root under the virtual 8-device CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python - < logs/drive_engine_async_verify.py

Checks, end-to-end as a consumer would drive them:
  1. pipelined == sequential, byte for byte, on the 8-device mesh —
     plain AND fedbuff AND with telemetry on; donation report clean.
  2. a 10x-skewed TrainerSpeedPlan lowered to a FedBuffSchedule: the
     staleness fan-out (gauge, ledger staleness/version stamps,
     AsyncController feed) and the τ=0 ≡ sync bit-parity receipt.
  3. FederationLearner rides ENGINE_PREFETCH perf-only: on/off byte
     identity, no leaked prefetch threads.
  4. the bench engine_async tier booleans (throughput under skew,
     idle-gap cut, determinism).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np

from tpfl.communication.faults import TrainerSpeedPlan
from tpfl.learning.async_control import AsyncController
from tpfl.management import ledger
from tpfl.management.telemetry import flight, metrics
from tpfl.models import MLP
from tpfl.parallel import (
    FederationEngine,
    FedBuffSchedule,
    WindowPipeline,
    create_mesh,
)
from tpfl.settings import Settings

Settings.set_test_settings()

assert jax.device_count() >= 8, jax.devices()
mesh = create_mesh({"nodes": 8})
N, R, W = 8, 6, 2


def data(seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.random((N, 2, 8, 28, 28)).astype(np.float32),
        rng.integers(0, 10, (N, 2, 8)).astype(np.int32),
    )


def tree_bytes(t):
    return b"".join(
        np.asarray(x).tobytes() for x in jax.tree_util.tree_leaves(t)
    )


def engine():
    return FederationEngine(
        MLP(hidden_sizes=(16,), compute_dtype=jnp.float32),
        N, mesh=mesh, seed=0,
    )


def sched():
    return FedBuffSchedule.from_periods([1, 1, 1, 1, 2, 2, 3, 3], R)


# --- 1. pipelined == sequential on the mesh, donation clean ---------------
for label, use_sched, tele in (
    ("plain", False, False),
    ("fedbuff", True, False),
    ("fedbuff+telemetry", True, True),
):
    Settings.ENGINE_TELEMETRY = tele
    outs = []
    for pipelined in (False, True):
        eng = engine()
        p = eng.init_params((28, 28))
        dx, dy = eng.shard_data(*data())
        s = sched() if use_sched else None
        if pipelined:
            (p, losses), done = WindowPipeline(eng).run(
                p, dx, dy, n_rounds=R, window=W, schedule=s
            )
            assert done == R
        else:
            done = 0
            while done < R:
                k = min(W, R - done)
                p, losses = eng.run_rounds(
                    p, dx, dy, n_rounds=k,
                    schedule=None if s is None else s.window(done, k),
                )
                done += k
        outs.append((tree_bytes(p), tree_bytes(losses)))
    assert outs[0] == outs[1], f"pipelined != sequential ({label})"
    print(f"[1] pipelined == sequential bytes @8dev ({label}): OK")
Settings.ENGINE_TELEMETRY = False

eng = engine()
p = eng.init_params((28, 28))
dx, dy = eng.shard_data(*data())
rep = eng.donation_report(p, dx, dy, n_rounds=2)
assert rep["clean"], rep
print("[1] donation report clean @8dev: OK")

# --- 2. skewed plan -> schedule -> staleness fan-out ----------------------
addrs = [f"engine-node-{i}" for i in range(N)]
plan = TrainerSpeedPlan.skewed(
    addrs, slow_frac=0.25, base_delay=0.05, skew=10.0, seed=7
)
R2 = 20  # enough rounds for the 10x-slow tail to actually arrive
ps = FedBuffSchedule.from_plan(plan, addrs, R2)
ps2 = FedBuffSchedule.from_plan(plan, addrs, R2)
assert np.array_equal(ps.arrivals, ps2.arrivals)
assert np.array_equal(ps.taus, ps2.taus)
assert (ps.arrivals.sum(axis=1) > 0).all()
assert ps.taus.max() > 0, "skewed tail produced no stale arrivals"
print(f"[2] speed-plan lowering deterministic (max tau {ps.taus.max():.0f}): OK")

# τ=0 all-arrive schedule ≡ sync program, bit for bit.
eng = engine()
p0 = eng.init_params((28, 28))
dx, dy = eng.shard_data(*data())
allin = FedBuffSchedule.from_periods([1] * N, 3)
a, _ = eng.run_rounds(p0, dx, dy, n_rounds=3, donate=False)
b, _ = eng.run_rounds(p0, dx, dy, n_rounds=3, donate=False, schedule=allin)
assert tree_bytes(a) == tree_bytes(b)
print("[2] tau=0 fedbuff == sync bytes: OK")

Settings.ENGINE_TELEMETRY = True
Settings.LEDGER_ENABLED = True
Settings.ASYNC_ADAPTIVE = True
ledger.contrib.reset()
eng = engine()
ctrl = AsyncController("drive")
eng.controller = ctrl
p = eng.init_params((28, 28))
dx, dy = eng.shard_data(*data())
eng.run_rounds(p, dx, dy, n_rounds=R2, schedule=ps)
prom = metrics.render_prometheus()
assert "tpfl_engine_staleness" in prom
entries = [
    e for e in ledger.contrib.entries()
    if str(e.get("peer", "")).startswith("engine-node-")
]
assert entries and all("staleness" in e and "version" in e for e in entries)
assert all(e["version"] == e["round"] - e["staleness"] for e in entries)
assert int(ps.arrivals.sum()) == len(entries)
assert ctrl._last_arrivals == int(ps.arrivals[-1].sum())
assert ctrl._tau_mean is not None
print(
    f"[2] staleness fan-out ({len(entries)} ledger entries == "
    f"{int(ps.arrivals.sum())} arrivals, controller fed): OK"
)
ledger.contrib.reset()
flight.clear()
Settings.set_test_settings()

# --- 3. FederationLearner ENGINE_PREFETCH perf-only -----------------------
from tpfl.learning.dataset import synthetic_mnist
from tpfl.models import create_model
from tpfl.parallel import FederationLearner

ds = synthetic_mnist(n_train=640, n_test=128, seed=0, noise=0.4)


def fit_bytes(prefetch):
    Settings.ENGINE_PREFETCH = prefetch
    Settings.SHARD_ROUNDS_PER_DISPATCH = 2
    fl = FederationLearner(
        model=create_model("mlp", (28, 28), seed=7, hidden_sizes=(16,)),
        data=ds,
        n_local_nodes=N,
        local_rounds=R,
        batch_size=16,
        seed=0,
        mesh=mesh,
    )
    model = fl.fit()
    return tree_bytes(model.get_parameters())


b_off = fit_bytes(False)
b_on = fit_bytes(True)
assert b_off == b_on, "ENGINE_PREFETCH changed bytes"
leaked = [t for t in threading.enumerate() if "prefetch" in t.name]
assert not leaked, leaked
print("[3] FederationLearner ENGINE_PREFETCH on/off byte-identical, no leaked threads: OK")
Settings.set_test_settings()

# --- 4. bench engine_async tier booleans ----------------------------------
import bench

e = {}
bench._engine_async_tier(e)
assert "engine_async_error" not in e, e.get("engine_async_error")
t = e["engine_async_throughput"]
assert t["fedbuff_holds_0_8x"] and t["sync_degrades"], t
pl = e["engine_async_pipeline"]
assert pl["gap_cut_2x"] and pl["bytes_identical"], pl
d = e["engine_async_determinism"]
assert d["byte_identical_1dev"] and d["byte_identical_8dev"], d
print(
    f"[4] bench tier: fedbuff {t['fedbuff_vs_unskewed']}x unskewed "
    f"(sync {t['sync_vs_unskewed']}x), gap {pl['seq_idle_gap_s']}s -> "
    f"{pl['pipeline_idle_gap_s']}s, determinism 1+8dev: OK"
)

print("ALL ENGINE-ASYNC DRIVE CHECKS PASSED")
