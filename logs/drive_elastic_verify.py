"""Drive script: elastic engine + kill-and-resume (ISSUE 17).

Run from the repo root under the CPU-mesh env:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python - < logs/drive_elastic_verify.py

Covers, end to end on an 8-virtual-device mesh:
  1. churn storm through a MembershipView with ZERO recompiles at a
     fixed capacity tier (CompileObservatory receipt), the one tier
     promotion compiling exactly one new program;
  2. masked capacity-8 run (4 live) byte-identical to a fresh exact
     n=4 run on the same mesh;
  3. kill-and-resume: EngineCheckpointer disk round trip onto a FRESH
     engine — byte-identical to uninterrupted on the same mesh, and a
     cross-mesh (1-device -> 8-device) restore at numeric tolerance —
     with AsyncController + QuarantineEngine state surviving;
  4. SIGTERM -> final checkpoint on disk (handler chains + restores);
  5. WindowPipeline cadence snapshots (published through the
     checkpointer, rounds pinned) and interrupt_for() abandon;
  6. COMPILE_CACHE_DIR knob: persistent jax compilation cache armed,
     tpfl_compile_cache_warm_total counter registered.
"""
import os
import signal
import tempfile

import jax
import numpy as np

from tpfl.learning.async_control import AsyncController
from tpfl.management import profiling
from tpfl.management.checkpoint import (
    EngineCheckpointer,
    install_sigterm_checkpoint,
)
from tpfl.management.quarantine import QuarantineEngine
from tpfl.models import MLP
from tpfl.parallel import FederationEngine, WindowPipeline, create_mesh
from tpfl.parallel.membership import MembershipView
from tpfl.parallel.window_pipeline import interrupt_for
from tpfl.settings import Settings

Settings.set_test_settings()
assert jax.device_count() >= 8, "run under the 8-virtual-device env"
mesh8 = create_mesh({"nodes": 8})


def data(n, nb=1, bs=32, seed=13):
    rng = np.random.default_rng(seed)
    return (rng.random((n, nb, bs, 28, 28), np.float32),
            rng.integers(0, 10, (n, nb, bs)).astype(np.int32))


def engine(n, mesh=None):
    return FederationEngine(MLP(hidden_sizes=(16,)), n, mesh=mesh,
                            learning_rate=0.1, seed=0)


def tree_bytes(t):
    return b"".join(np.asarray(x).tobytes()
                    for x in jax.tree_util.tree_leaves(t))


# 1. churn storm, zero recompiles -------------------------------------
view = MembershipView([f"n{i}" for i in range(4)], capacity_min=4)
eng = engine(4)
eng.attach_membership(view)
p = eng.init_params((28, 28))
xs8, ys8 = data(8)
dx, dy = eng.shard_data(xs8[:4], ys8[:4])
Settings.PROFILING_ENABLED = True
profiling.observatory.reset()
events = [("leave", "n1"), ("join", "n1"), ("crash", "n2"), ("join", "n2"),
          ("quarantine", "n3"), ("readmit", "n3"), ("join", "n4")]
for r in range(12):
    if r < len(events):
        kind, addr = events[r]
        getattr(view, kind)(addr)
    u = eng.unpad(p)
    if eng.sync_membership():
        p = eng.pad_stacked(u)
        dx, dy = eng.shard_data(xs8[:eng.n_nodes], ys8[:eng.n_nodes])
    p, _ = eng.run_rounds(p, dx, dy, weights=view.weights(), n_rounds=1,
                          donate=False)
counts = {k: v for k, v in profiling.observatory.signature_counts().items()
          if k.startswith("engine_round")}
Settings.PROFILING_ENABLED = False
assert counts and all(v == 1 for v in counts.values()), counts
assert sum(counts.values()) - 1 == view.promotions() == 1, counts
print("1. churn storm: zero recompiles, 1 promotion ->", sorted(counts))

# 2. masked capacity-8 == exact n=4 on the same mesh ------------------
xs4, ys4 = data(4)
exact = engine(4, mesh=mesh8)
pe = exact.init_params((28, 28))
dxe, dye = exact.shard_data(xs4, ys4)
out_e, _ = exact.run_rounds(pe, dxe, dye, n_rounds=2, donate=False)
v8 = MembershipView([f"n{i}" for i in range(4)], capacity_min=8)
el = engine(8, mesh=mesh8)
el.attach_membership(v8)
pad = lambda a: np.concatenate([a, np.broadcast_to(a[:1], (4, *a.shape[1:]))])
dx8, dy8 = el.shard_data(pad(xs4), pad(ys4))
out_8, _ = el.run_rounds(el.pad_stacked(exact.unpad(pe)), dx8, dy8,
                         weights=v8.weights(), n_rounds=2, donate=False)
live = lambda t: jax.tree_util.tree_map(lambda x: np.asarray(x)[:4], t)
assert tree_bytes(live(out_8)) == tree_bytes(live(out_e))
print("2. masked capacity-8 run byte-identical to exact n=4")

# 3. kill-and-resume (same mesh bytes, cross-mesh tolerance) ----------
eng_a = engine(4)
pa = eng_a.init_params((28, 28))
dxa, dya = eng_a.shard_data(xs4, ys4)
pa, _ = eng_a.run_rounds(pa, dxa, dya, n_rounds=6, donate=False)
eng_b = engine(4)
pb = eng_b.init_params((28, 28))
dxb, dyb = eng_b.shard_data(xs4, ys4)
pb, _ = eng_b.run_rounds(pb, dxb, dyb, n_rounds=3, donate=False)
ctl = AsyncController(node_name="drive")
ctl.state_import({"tau_mean": 1.5, "k": 3,
                  "trajectory": [{"round": 3, "k": 2, "deadline": 1.0}]})
q = QuarantineEngine("drive")
q.state_import({
    "state": {"bad": {"active": True, "since_round": 2,
                      "last_flag_round": 2, "probation": 0}},
    "actions": [], "last": {"bad": [2, {"exclude": True}]},
})
eng_b.controller = ctl
with tempfile.TemporaryDirectory() as td:
    ck = EngineCheckpointer(td, node="drive")
    ck.save(eng_b.export_state(pb, quarantine=q), step=3)
    state, meta = ck.restore()
eng_c = engine(4)
ctl2, q2 = AsyncController(node_name="drive2"), QuarantineEngine("drive2")
eng_c.controller = ctl2
out = eng_c.import_state(state, quarantine=q2)
dxc, dyc = eng_c.shard_data(xs4, ys4)
pc, _ = eng_c.run_rounds(out["params"], dxc, dyc, n_rounds=3, donate=False)
assert tree_bytes(eng_a.unpad(pa)) == tree_bytes(eng_c.unpad(pc))
assert meta["step"] == 3 and eng_c._rounds_done == 6
restored = ctl2.state_export()
assert restored["tau_mean"] == 1.5 and restored["k"] == 3
assert restored["trajectory"][0]["round"] == 3
assert q2.quarantined() == {"bad"}
# cross-mesh: restore the same snapshot onto the 8-device mesh
eng_m = engine(4, mesh=mesh8)
out_m = eng_m.import_state(state)
dxm, dym = eng_m.shard_data(xs4, ys4)
pm, _ = eng_m.run_rounds(out_m["params"], dxm, dym, n_rounds=3, donate=False)
for a, b in zip(jax.tree_util.tree_leaves(eng_a.unpad(pa)),
                jax.tree_util.tree_leaves(eng_m.unpad(pm))):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)
print("3. kill-and-resume: same-mesh bytes, cross-mesh allclose,"
      " controller/quarantine state restored")

# 4. SIGTERM -> final checkpoint --------------------------------------
with tempfile.TemporaryDirectory() as td:
    ck = EngineCheckpointer(td, node="drive")
    prev = install_sigterm_checkpoint(
        ck, lambda: eng_b.export_state(pb), node="drive")
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        state, meta = ck.restore()
        assert meta["reason"] == "sigterm" and meta["step"] == 3
    finally:
        signal.signal(signal.SIGTERM, prev)
print("4. SIGTERM handler published a final checkpoint (step 3)")

# 5. pipeline cadence snapshots + interrupt ---------------------------
eng_s = engine(4)
ps = eng_s.init_params((28, 28))
dxs, dys = eng_s.shard_data(xs4, ys4)
snaps = []
pipe = WindowPipeline(eng_s)
res, done = pipe.run(ps, dxs, dys, n_rounds=6, window=2, donate=False,
                     snapshot_every=1, snapshot_to=lambda r, s:
                     snaps.append((r, s)))
assert done == 6 and [r for r, _ in snaps] == [2, 4, 6]
assert tree_bytes(snaps[-1][1]["params"]) == tree_bytes(eng_s.unpad(res[0]))
eng_i = engine(4)
pi = eng_i.init_params((28, 28))
dxi, dyi = eng_i.shard_data(xs4, ys4)
hits = []

def wf(widx):
    hits.append(widx)
    if widx == 1:
        assert interrupt_for("drive-addr")
    return None

pipe_i = WindowPipeline(eng_i)
res_i, done_i = pipe_i.run(pi, dxi, dyi, n_rounds=6, window=2,
                           donate=False, weights_for=wf,
                           owner="drive-addr")
assert res_i is None and done_i == 4 and hits == [0, 1]
assert not interrupt_for("drive-addr")  # registry cleaned
print("5. cadence snapshots pinned + interrupt_for abandoned cleanly")

# 6. persistent compile cache knob ------------------------------------
from tpfl.management.telemetry import metrics

with tempfile.TemporaryDirectory() as td:
    Settings.COMPILE_CACHE_DIR = td
    for _ in range(2):  # 2nd identical program warms from the dir
        eng_k = engine(2)
        pk = eng_k.init_params((28, 28))
        dxk, dyk = eng_k.shard_data(*data(2))
        eng_k.run_rounds(pk, dxk, dyk, n_rounds=1, donate=False)
    assert profiling._COMPILE_CACHE_DIR == td
    assert jax.config.jax_compilation_cache_dir == td
    Settings.COMPILE_CACHE_DIR = ""
warm = {k: v for k, v in metrics.fold()["counters"].items()
        if "compile_cache_warm" in k[0]}
assert warm and all(v > 0 for v in warm.values()), \
    "tpfl_compile_cache_warm_total never counted"
print("6. COMPILE_CACHE_DIR armed; warm counter ->", warm)

print("ELASTIC DRIVE OK")
