"""Drive: the 2D nodes x model mesh in the engine round program
(ISSUE 15). Run from the repo root under the CPU-mesh env:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python - < logs/drive_mesh2d_verify.py

Covers: SHARD_MODEL auto-mesh resolution, the federated TransformerLM
end-to-end on 4x2 (parity vs single device, per-device shard-bytes
drop, ring attention active, clean donation), the 1D HLO byte-identity
pin, fixed-mesh-shape determinism, the device codec on 2D, and the
transformer_fed bench tier.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from tpfl.models import MLP, TransformerLM
from tpfl.parallel import FederationEngine, create_mesh, layout_for_module
from tpfl.settings import Settings

Settings.set_test_settings()
assert len(jax.devices()) == 8, jax.devices()

n, nb, bs, S = 8, 1, 2, 16
module = TransformerLM(
    vocab=64, dim=32, heads=4, n_layers=2, max_len=64,
    compute_dtype=jnp.float32,
)
rng = np.random.default_rng(0)
xs = rng.integers(0, 64, (n, nb, bs, S)).astype(np.int32)
ys = rng.integers(0, 64, (n, nb, bs, S)).astype(np.int32)
w = np.asarray([1, 1, 0, 1, 0, 1, 1, 1], np.float32)

# 1. SHARD_MODEL auto-mesh resolution.
Settings.SHARD_NODES, Settings.SHARD_MODEL = True, 2
eng_auto = FederationEngine(module, n, mesh="auto", seed=0)
assert eng_auto.mesh.shape == {"nodes": 4, "model": 2}, eng_auto.mesh.shape
assert eng_auto.model_axes == 2 and eng_auto.layout.name == "transformer"
Settings.SHARD_NODES, Settings.SHARD_MODEL = False, 1
print("[1] SHARD_MODEL=2 auto mesh -> 4x2, transformer layout")

# 2. End-to-end federated TransformerLM: 4x2 vs single device.
def run(mesh):
    eng = FederationEngine(module, n, mesh=mesh, seed=0, learning_rate=0.05)
    p = eng.init_params((S,))
    dx, dy = eng.shard_data(xs, ys)
    p, losses = eng.run_rounds(p, dx, dy, weights=w, n_rounds=2)
    return eng, p, losses

mesh42 = create_mesh({"nodes": 4, "model": 2})
eng1, p1, l1 = run(None)
eng2, p2, l2 = run(mesh42)
# Ring attention was swapped in (the module clone seam).
assert eng2.module is not module and eng2.module.attention_fn is not None
assert eng1.module is module
for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=5e-4)
leaves = jax.tree_util.tree_leaves(p2)
total = sum(x.nbytes for x in leaves)
per_dev = sum(x.addressable_shards[0].data.nbytes for x in leaves)
assert total / per_dev > 6, (total, per_dev)  # > nodes-only 4x
print(f"[2] 4x2 LM parity OK, ring attention active, "
      f"per-device bytes 1/{total / per_dev:.2f} of stacked")

# 3. 1D HLO byte-identity pin (model=1 engages zero 2D machinery).
def digest(mesh):
    eng = FederationEngine(
        MLP(hidden_sizes=(16,), compute_dtype=jnp.float32), n, mesh=mesh,
        seed=0,
    )
    fn = eng.program("plain", 1, 2, 1, donate=False,
                     model_axes=eng.model_axes, layout=eng.layout.name)
    p = eng.init_params((28, 28))
    mx = rng.random((n, nb, 4, 28, 28)).astype(np.float32)
    my = rng.integers(0, 10, (n, nb, 4)).astype(np.int32)
    dx, dy = eng.shard_data(mx, my)
    low = fn.lower(p, {}, {}, {}, dx, dy, eng.pad_weights(None), eng.valid)
    return hashlib.sha256(low.as_text().encode()).hexdigest()

assert digest(create_mesh({"nodes": 8})) == digest(
    create_mesh({"nodes": 8, "model": 1})
)
print("[3] nodes=8 x model=1 HLO digest == 1D nodes=8 mesh")

# 4. Fixed-mesh-shape same-seed byte determinism.
def model_bytes():
    _, p, _ = run(create_mesh({"nodes": 4, "model": 2}))
    return b"".join(
        np.asarray(x).tobytes() for x in jax.tree_util.tree_leaves(p)
    )

assert model_bytes() == model_bytes()
print("[4] same-seed 4x2 runs byte-identical")

# 5. Donation clean + device codec parity on the 2D program.
engD = FederationEngine(module, n, mesh=mesh42, seed=0, learning_rate=0.05)
pD = engD.init_params((S,))
dxD, dyD = engD.shard_data(xs, ys)
rep = engD.donation_report(pD, dxD, dyD, n_rounds=2)
assert rep["clean"], rep
Settings.ENGINE_WIRE_CODEC = "quant8"
try:
    _, q1, _ = run(None)
    _, q2, _ = run(mesh42)
    for a, b in zip(
        jax.tree_util.tree_leaves(q1), jax.tree_util.tree_leaves(q2)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
finally:
    Settings.ENGINE_WIRE_CODEC = "dense"
print(f"[5] 2D donation clean ({rep['output_aliases']}/"
      f"{rep['donated_leaves']} aliased), quant8 gossip parity OK")

# 6. Layout policy sanity (replicated default for MLP).
assert layout_for_module(MLP()).name == "replicated"

# 7. The transformer_fed bench tier, single-tier drive.
import bench

e = {}
bench._transformer_fed_tier(e)
t = e["transformer_fed"]
assert t["parity_within_2pct"] and t["determinism_byte_identical"]
assert t["donation_clean"] and t["shard_bytes_ratio"] >= 1.5, t
print(f"[7] transformer_fed tier: rps 1x1={t['rps_1x1']} "
      f"4x2={t['rps_4x2']}, shard drop {t['shard_bytes_ratio']}x")

print("DRIVE OK: 2D nodes x model mesh verified end-to-end")
