"""Drive: ISSUE-14 static-analysis suite + TRACE_CONTRACTS verification.

Run from the repo root: ``JAX_PLATFORMS=cpu python - < logs/drive_static_analysis_verify.py``
"""
from tpfl.settings import Settings

Settings.set_test_settings()
Settings.LOG_LEVEL = "ERROR"
from tpfl.management.logger import logger

logger.set_level("ERROR")

import jax.numpy as jnp

from tpfl.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
from tpfl.learning.jax_learner import JaxLearner
from tpfl.models import create_model
from tpfl.simulation.batched_fit import job_signature

# --- job_signature on device leaves (the fixed np.asarray copy path) ---
ds = synthetic_mnist(n_train=120, n_test=30, seed=0, noise=0.4)
part = ds.generate_partitions(1, RandomIIDPartitionStrategy, seed=1)[0]
model = create_model("mlp", (28, 28), seed=7, hidden_sizes=(16,))
model.set_parameters([jnp.asarray(p) for p in model.get_parameters_list()])
ln = JaxLearner(model, part, addr="sig-check-0")
sig = job_signature(ln)
assert sig[2] and all(dt == "float32" for _s, dt in sig[2]), sig[2]
model2 = create_model("mlp", (28, 28), seed=9, hidden_sizes=(16,))
assert job_signature(JaxLearner(model2, part, addr="sig-check-1")) == sig
print("job_signature OK on device leaves (no host copies), sharing intact")

# --- TRACE_CONTRACTS on the real engine seam ---
from tpfl.concurrency import TraceContractError
from tpfl.parallel.engine import FederationEngine

Settings.TRACE_CONTRACTS = True
module = create_model("mlp", (4,), seed=0, hidden_sizes=(8,)).module
eng = FederationEngine(module, 2, learning_rate=0.1, seed=0)
params = eng.init_params((4,))
xs = jnp.zeros((2, 1, 4, 4))
ys = jnp.zeros((2, 1, 4), jnp.int32)
out = eng.run_rounds(params, xs, ys, epochs=1, donate=False)
frac = float(Settings.WIRE_TOPK_FRAC)
# seeded key-hygiene bug: donation variants collide on one cache slot
eng._wrapped[("plain", 1, 1, 1, True, False, 0, 0, frac)] = (
    eng._wrapped[("plain", 1, 1, 1, False, False, 0, 0, frac)]
)
try:
    eng.run_rounds(out[0], xs, ys, epochs=1, donate=True)
    raise SystemExit("contract did NOT fire")
except TraceContractError as e:
    assert "ENGINE_DONATE" in str(e)
print("TRACE_CONTRACTS witness OK (names ENGINE_DONATE)")
Settings.TRACE_CONTRACTS = False
eng2 = FederationEngine(module, 2, learning_rate=0.1, seed=0)
eng2.run_rounds(eng2.init_params((4,)), xs, ys, epochs=1, donate=False)
assert not hasattr(next(iter(eng2._wrapped.values())), "contract")
print("contracts-off zero-wrapper OK")

# --- static suite + analysis tier (the CI gates' inputs) ---
import bench

e = {}
bench._analysis_tier(e)
s = e["analysis_static"]
assert s["zero_violations"] and s["jax_passes_clean"] and s["within_5s_budget"], s
assert e["analysis_lock_trace"]["traced"]["acyclic"]
assert e["analysis_lock_trace"]["traced"]["all_threads_named"]
print("analysis tier OK:", {k: s[k] for k in ("wall_s", "violations", "jax_pass_violations")})

# --- capture pass proves the engine key (the acceptance criterion) ---
import pathlib
import shutil
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path.cwd()))
from tools.tpflcheck.capture import check_capture

src = pathlib.Path("tpfl/parallel/engine.py").read_text()
with tempfile.TemporaryDirectory() as td:
    target = pathlib.Path(td) / "tpfl" / "parallel" / "engine.py"
    target.parent.mkdir(parents=True)
    for frag, param in [
        ("bool(donate),\n", "donate"), ("bool(telemetry), ", "telemetry"),
        ("int(codec), ", "codec"), ("float(topk_frac),", "topk_frac"),
    ]:
        target.write_text(src.replace(frag, "", 1))
        found = check_capture(pathlib.Path(td))
        assert any(v.key.endswith(f"::{param}") for v in found), (frag, found)
print("capture pass proves engine key totality (all 4 axes)")
print("ALL DRIVES PASSED")
