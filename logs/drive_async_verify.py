"""Drive: asynchronous buffered rounds (PR 10) — run from the repo root:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python - < logs/drive_async_verify.py

Covers: (1) the async aggregator data path over REAL wire bytes
(staleness-weighted fold of encode->decode round-tripped models,
buffer-full + deadline close reasons, empty-deadline fail-open),
(2) a free-running 4-node async federation e2e (decoupled trainer
loops, learns, trainer threads drain), (3) the serialized
byte-determinism receipt (two same-seed runs, speed-skewed fleet,
AsyncSchedule discipline), (4) the ring_attention flash SPMD fix
under the 8-device mesh, (5) deadline observability counters.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np

from tpfl.learning.aggregators import FedAvg
from tpfl.learning.aggregators.aggregator import staleness_weight
from tpfl.learning.model import TpflModel
from tpfl.management.logger import logger
from tpfl.settings import Settings

Settings.set_test_settings()
Settings.LOG_LEVEL = "ERROR"
logger.set_level("ERROR")

# --- (1) async aggregator over real wire bytes ---------------------------


def mk(value, n, contributors):
    params = {
        "w": jnp.full((4, 4), float(value), jnp.float32),
        "b": jnp.full((4,), float(value), jnp.float32),
    }
    return TpflModel(params=params, num_samples=n, contributors=contributors)


tmpl = mk(0.0, 1, ["tmpl"])
agg = FedAvg("drive")
agg.set_nodes_to_aggregate(["a", "b", "c"], async_k=2, round_ordinal=9)
# Contributions arrive as WIRE BYTES (encode -> build_copy), like a peer's.
for addr, val, ver in (("a", 2.0, 9), ("b", 6.0, 6)):
    m = mk(val, 50, [addr])
    wire = m.encode_parameters()
    rx = tmpl.build_copy(params=wire, contributors=[addr], num_samples=50)
    agg.add_model(rx, start_version=ver)
assert not agg.is_open() and agg.close_reason() == "buffer_full"
out = agg.wait_and_get_aggregation(timeout=2.0)
w_a, w_b = 50 * staleness_weight(0), 50 * staleness_weight(3)
want = (2.0 * w_a + 6.0 * w_b) / (w_a + w_b)
got = float(np.asarray(out.get_parameters()["w"])[0, 0])
assert abs(got - want) < 1e-5, (got, want)
agg.clear()
print(f"[1] async wire-bytes staleness fold OK (got {got:.4f} == {want:.4f})")

# Deadline semantics + counters.
agg.set_nodes_to_aggregate(["a", "b", "c"], async_k=3, round_ordinal=10)
assert agg.async_deadline_close() is False and agg.is_open()  # empty: fail open
agg.add_model(mk(1.0, 10, ["a"]), start_version=10)
assert agg.async_deadline_close() is True
assert agg.close_reason() == "deadline"
agg.wait_and_get_aggregation(timeout=2.0)
agg.clear()
folded = logger.metrics.fold()
dl = {
    dict(k[1]).get("outcome"): v
    for k, v in folded["counters"].items()
    if k[0] == "tpfl_agg_deadline_total"
}
assert dl.get("empty", 0) >= 1 and dl.get("closed", 0) >= 1, dl
print(f"[1] deadline fail-open + close + counters OK ({dl})")

# --- (2) free-running 4-node async federation ----------------------------

from tpfl.attacks import metric_table, run_seeded_experiment  # noqa: E402

Settings.ASYNC_ROUNDS = True
Settings.ASYNC_BUFFER_K = 3
Settings.ASYNC_SERIALIZED = False
t0 = time.monotonic()
exp = run_seeded_experiment(
    1207, 4, 5, epochs=3, samples_per_node=100, batch_size=20, timeout=240.0
)
el = time.monotonic() - t0
tbl = metric_table(exp)
accs = [tbl[n]["test_metric"][-1][1] for n in sorted(tbl)]
acc = sum(accs) / len(accs)
assert acc > 0.25, accs
deadline = time.monotonic() + 10.0
while time.monotonic() < deadline and any(
    t.name.startswith("async-trainer-") for t in threading.enumerate()
):
    time.sleep(0.1)
assert not any(
    t.name.startswith("async-trainer-") and t.is_alive()
    for t in threading.enumerate()
), "trainer loops must drain at experiment end"
print(f"[2] free-running 4-node e2e OK (acc {acc:.2f}, {el:.1f}s, loops drained)")

# --- (3) serialized byte-determinism receipt ------------------------------

from tpfl.attacks.harness import final_model_digests  # noqa: E402
from tpfl.communication.faults import TrainerSpeedPlan  # noqa: E402

Settings.ASYNC_SERIALIZED = True
Settings.DISABLE_SIMULATION = True


def det_run():
    plan = TrainerSpeedPlan.skewed(
        [f"seed1209-n{i}" for i in range(4)],
        slow_frac=0.25, base_delay=0.05, skew=10.0, seed=1209,
    )
    e = run_seeded_experiment(
        1209, 4, 3, epochs=1, speed_plan=plan,
        samples_per_node=60, batch_size=20, timeout=240.0,
    )
    return final_model_digests(e)


d1, d2 = det_run(), det_run()
assert d1 == d2, "same-seed serialized runs must be byte-identical"
assert len(set(d1.values())) == 1, "all nodes must converge on identical bytes"
Settings.DISABLE_SIMULATION = False
Settings.ASYNC_ROUNDS = False
print(f"[3] serialized byte-determinism OK (digest {sorted(set(d1.values()))[0][:16]}…)")

# --- (4) ring_attention flash SPMD (the fixed tier-1 failure) -------------

from functools import partial  # noqa: E402

import jax  # noqa: E402
from jax.sharding import PartitionSpec  # noqa: E402

from tpfl.parallel import create_mesh  # noqa: E402
from tpfl.parallel.ring_attention import make_ring_attention  # noqa: E402

rng = np.random.default_rng(0)
B, S, H, D = 2, 64, 4, 16
q, k, v = (
    jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32) for _ in range(3)
)
mesh = create_mesh({"sp": 8})
for causal in (False, True):
    ring = make_ring_attention(mesh, causal=causal, impl="flash")
    out = ring(q, k, v)  # used to die: PartitionId under SPMD partitioning
    assert out.shape == (B, S, H, D)
print("[4] ring_attention flash SPMD OK (causal and non-causal, 8-device mesh)")

print("DRIVE OK: async buffered rounds verified end-to-end")
