"""Fleet-observatory verify drive (ISSUE 20).

Run from the repo root under the CPU-mesh env:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python - < logs/drive_fleetobs_verify.py

Covers, end to end on real objects (no mocks, no pytest):

  (a) snapshot/fold federation: wire round-trip, origin labels, fold
      order-independence at the byte level, prefix filtering;
  (b) FleetPublisher -> fleet dir -> fleet_from_dir, torn files skipped;
  (c) a REAL 2-process jax.distributed (gloo) launch twice at the same
      seed: per-rank snapshots carry origin + only deterministic
      prefixes, the folded fleet view renders BYTE-IDENTICAL;
  (d) SLO watchdog: target grammar errors, warm-up, an injected ~20%
      rounds/sec regression breached within SLO_BREACH_WINDOWS,
      single-shot events + re-arm on recovery, uninjected silent;
  (e) live endpoints: /metrics, /healthz 200 -> 503 across a breach,
      /fleet.json merged view, traceview --fleet over live HTTP;
  (f) population observatory: coverage/fairness/staleness sketches on a
      real ClientPopulation, tpfl_pop_* fan-out, population_round
      flight events joined with quarantine actions in traceview,
      sketch state round-trip (bytes bitset) + legacy rebuild;
  (g) engine attach registrations + emit_fleet_gauges + NodeMonitor;
  (h) the tpflcheck metrics lint: full suite green, plus a doctored
      mini-repo proof that an undocumented tpfl_* name is caught;
  (i) the bench `fleetobs` tier booleans (merged determinism, watchdog
      catch, overhead budget, pop-sketch RSS bound).
"""

import json
import math
import os
import pathlib
import tempfile
import urllib.error
import urllib.request

import numpy as np

from tpfl.management import fleetobs
from tpfl.management.telemetry import MetricsRegistry, flight, metrics
from tpfl.settings import Settings

Settings.set_test_settings()


def check(cond, msg):
    if not cond:
        raise SystemExit(f"FAIL: {msg}")
    print(f"  ok: {msg}")


# --- (a) snapshot / fold federation ----------------------------------------
print("[a] snapshot/fold federation")
regs = []
for rank in range(2):
    r = MetricsRegistry()
    r.counter("tpfl_engine_rounds_total", 3.0 + rank, labels={"model": "m"})
    r.gauge("tpfl_engine_loss", 0.5 - 0.1 * rank, labels={"model": "m"})
    r.observe("tpfl_pop_staleness", 2.0, labels={"node": "population"})
    r.gauge("tpfl_system_cpu_percent", 50.0)  # outside the filter
    regs.append(r)
snaps = [
    fleetobs.snapshot(
        registry=regs[i],
        origin=str(i),
        prefixes=fleetobs.DETERMINISTIC_PREFIXES,
    )
    for i in range(2)
]
snaps = [json.loads(json.dumps(s)) for s in snaps]  # wire round-trip
for i, s in enumerate(snaps):
    check(s["origin"] == str(i), f"snapshot {i} stamps origin")
    names = {fleetobs._parse_series(k)[0] for k in s["counters"]} | {
        fleetobs._parse_series(k)[0] for k in s["gauges"]
    }
    check(
        all(
            n.startswith(fleetobs.DETERMINISTIC_PREFIXES) for n in names
        ),
        f"snapshot {i} filtered to deterministic prefixes",
    )
text01 = fleetobs.fold(snaps).render_prometheus()
text10 = fleetobs.fold(list(reversed(snaps))).render_prometheus()
check(text01 == text10, "fold is order-independent at the byte level")
check(
    'tpfl_engine_rounds_total{model="m",origin="1"} 4' in text01,
    "fold rewrites series with origin labels",
)
check("tpfl_system_cpu_percent" not in text01, "filter excluded system")

# --- (b) publisher + fleet dir ---------------------------------------------
print("[b] FleetPublisher -> fleet dir -> fleet_from_dir")
with tempfile.TemporaryDirectory() as d:
    for i in range(2):
        pub = fleetobs.FleetPublisher(
            f"host{i}", directory=d, registry=regs[i]
        )
        path = pub.publish_once()
        check(
            path is not None and os.path.basename(path) == f"fleetsnap-host{i}.json",
            f"publisher {i} wrote its snapshot file",
        )
    # A torn/partial file must be skipped, not crash the fold.
    (pathlib.Path(d) / "fleetsnap-torn.json").write_text("{ nope")
    merged = fleetobs.fleet_from_dir(d).render_prometheus()
    check(
        'origin="host0"' in merged and 'origin="host1"' in merged,
        "fleet_from_dir folds every intact publisher",
    )
check(
    fleetobs.FleetPublisher("x", directory=None).publish_once() is None,
    "publisher without a directory is disabled",
)

# --- (c) REAL 2-process cross-host federation ------------------------------
print("[c] 2-process gloo launch x2 (same seed): merged view determinism")
from tpfl.parallel import crosshost

knobs = {"SHARD_NODES": True, "SHARD_HOSTS": 0, "ENGINE_TELEMETRY": True}
texts = []
for attempt in range(2):
    results = crosshost.launch(
        num_processes=2, devices_per_proc=4, rounds=2, knobs=knobs
    )
    for r in results:
        snap = r["metrics_snapshot"]
        check(
            snap["origin"] == str(r["process_id"]),
            f"run {attempt}: rank {r['process_id']} snapshot origin",
        )
        check(
            bool(snap["counters"]) and bool(snap["gauges"]),
            f"run {attempt}: rank {r['process_id']} emitted series",
        )
    texts.append(fleetobs.fold_receipts(results).render_prometheus())
check(
    'origin="0"' in texts[0] and 'origin="1"' in texts[0],
    "merged fleet registry carries every rank's origin",
)
check("tpfl_engine_rounds_total" in texts[0], "engine series federated")
check(texts[0] == texts[1], "merged view BYTE-IDENTICAL across same-seed runs")

# --- (d) SLO watchdog -------------------------------------------------------
print("[d] SLO watchdog: grammar, warm-up, breach-within-2, re-arm")
for bad, msg in [
    ("bogus", "unparseable SLO clause"),
    ("ratio(tpfl_a) >= 1", "needs two metrics"),
    ("rate(tpfl_a, tpfl_b) >= 1", "takes one metric"),
]:
    try:
        fleetobs.parse_targets(bad)
        raise SystemExit(f"FAIL: {bad!r} should not parse")
    except ValueError as e:
        check(msg in str(e), f"grammar rejects {bad!r}")

wreg = MetricsRegistry()
wd = fleetobs.SLOWatchdog(
    "rate(tpfl_engine_rounds_total) >= 2.4",
    registry=wreg,
    node="drive-watchdog",
)
flight.clear("drive-watchdog")
total, now = 0.0, 0.0
verdicts = wd.evaluate(now=now)
check(
    verdicts[0]["signal"] is None and verdicts[0]["healthy"],
    "warm-up window has no signal and stays healthy",
)


def window(rate):
    global total, now
    total += rate
    now += 1.0
    wreg.counter("tpfl_engine_rounds_total", rate)
    return wd.evaluate(now=now)[0]


for _ in range(4):
    v = window(2.5)
    check(v["healthy"] and not v["breached"], "healthy window stays silent")
breach_at = None
for i in range(1, Settings.SLO_BREACH_WINDOWS + 2):
    v = window(2.0)  # the injected ~20% regression
    if v["breached"]:
        breach_at = i
        break
check(
    breach_at is not None and breach_at <= Settings.SLO_BREACH_WINDOWS + 1,
    f"injected regression breached in {breach_at} windows (<= 2 + warmup)",
)
events = [
    e for e in flight.snapshot("drive-watchdog") if e.get("name") == "slo_breach"
]
check(len(events) == 1, "exactly one slo_breach event fired")
check(
    events[0]["threshold"] == 2.4 and events[0]["signal"] < 2.4,
    "breach event carries target threshold + failing signal",
)
window(2.0)
check(
    len([e for e in flight.snapshot("drive-watchdog") if e.get("name") == "slo_breach"]) == 1,
    "sustained breach does not re-fire",
)
for _ in range(8):
    v = window(3.5)
check(v["healthy"], "recovery re-arms the target")
breach_counters = [
    val
    for (name, labels), val in metrics.fold()["counters"].items()
    if name == "tpfl_slo_breach_total"
    and any(k == "target" and wd._targets[0].key in v for k, v in labels)
]
check(breach_counters == [1.0], "tpfl_slo_breach_total == 1.0")

# Uninjected control: a steady healthy rate must stay silent.
qreg = MetricsRegistry()
qd = fleetobs.SLOWatchdog(
    "rate(tpfl_engine_rounds_total) >= 2.4", registry=qreg, node="drive-quiet"
)
flight.clear("drive-quiet")
qd.evaluate(now=0.0)
qt = 0.0
for i in range(1, 9):
    qt += 2.5
    qreg.counter("tpfl_engine_rounds_total", 2.5)
    v = qd.evaluate(now=float(i))[0]
    check(v["healthy"], f"uninjected window {i} healthy")
check(
    not [e for e in flight.snapshot("drive-quiet") if e.get("name") == "slo_breach"],
    "uninjected run fired zero breach events",
)

# --- (e) live endpoints -----------------------------------------------------
print("[e] /metrics + /healthz + /fleet.json + traceview --fleet over HTTP")
from tpfl.management.web_services import MetricsHTTPServer

with tempfile.TemporaryDirectory() as d:
    for i in range(2):
        fleetobs.FleetPublisher(
            f"r{i}", directory=d, registry=regs[i]
        ).publish_once()
    sreg = MetricsRegistry()
    sreg.counter("tpfl_engine_rounds_total", 7.0)
    swd = fleetobs.SLOWatchdog(
        "gauge(tpfl_engine_loss) <= 1.0", registry=sreg, node="drive-server"
    )
    srv = MetricsHTTPServer(0, registry=sreg, watchdog=swd, fleet_dir=d)
    port = srv.start()
    try:
        base = f"http://127.0.0.1:{port}"
        body = urllib.request.urlopen(f"{base}/healthz").read().decode()
        check('"healthy": true' in body or "ok" in body.lower(), "/healthz 200 while healthy")
        fleet = json.loads(urllib.request.urlopen(f"{base}/fleet.json").read())
        ckeys = list(fleet.get("counters", fleet))
        check(
            any("origin=r0" in k or 'origin="r0"' in k for k in ckeys)
            or any("origin" in k for k in ckeys),
            "/fleet.json serves the merged origin-labelled view",
        )
        promtext = urllib.request.urlopen(f"{base}/metrics").read().decode()
        check("tpfl_engine_rounds_total" in promtext, "/metrics serves the registry")

        # traceview reads the live endpoint like a dump file.
        import tools.traceview as traceview

        docs = traceview.load_metric_dumps([f"{base}/metrics.json"])
        check(
            f"127.0.0.1:{port}" in docs, "traceview keys live dumps by netloc"
        )
        fv = traceview.fleet_view(docs)
        check(
            any("origin=" in k for k in fv["counters"])
            and f"127.0.0.1:{port}" in fv["nodes"],
            "traceview --fleet rewrites live series with origin",
        )

        # Drive the watchdog unhealthy; /healthz must flip to 503.
        sreg.gauge("tpfl_engine_loss", 5.0)
        swd.evaluate(now=0.0)
        for i in range(1, Settings.SLO_BREACH_WINDOWS + 2):
            swd.evaluate(now=float(i))
        check(not swd.healthy(), "watchdog unhealthy after sustained breach")
        try:
            urllib.request.urlopen(f"{base}/healthz")
            raise SystemExit("FAIL: /healthz should be 503 after breach")
        except urllib.error.HTTPError as e:
            check(e.code == 503, "/healthz flips to 503 on breach")
    finally:
        srv.stop()

# --- (f) population observatory --------------------------------------------
print("[f] population sketches + tpfl_pop_* fan-out + traceview join")
from tpfl.parallel import ClientPopulation

flight.clear("population")
pop = ClientPopulation(registered=512, sample=8, seed=3)
ids = pop.begin_round()
w = pop.round_weights(ids, cutoff_frac=0.25)
pop.complete_round(ids, w, np.full(len(ids), 0.4, np.float32))
check(pop.coverage == 8 / 512, "coverage == sampled/registered after r0")
check(0.0 < pop.fairness <= 1.0, "fairness in (0, 1]")
check(pop.touched == int((w > 0).sum()), "touched counts folders only")
pfold = metrics.fold()
pg = {
    name: val
    for (name, labels), val in pfold["gauges"].items()
    if name.startswith("tpfl_pop_") and ("node", "population") in labels
}
check(
    math.isclose(pg["tpfl_pop_coverage"], pop.coverage),
    "tpfl_pop_coverage gauge matches the sketch",
)
check(pg["tpfl_pop_census"] == 512.0, "tpfl_pop_census gauge")
evs = [
    e for e in flight.snapshot("population") if e.get("name") == "population_round"
]
check(len(evs) == 1 and evs[0]["census"] == 512, "population_round flight event")

# traceview join: quarantine action lands in the same round's row.
import tools.traceview as traceview

timeline = {"population": list(flight.snapshot("population"))}
timeline["population"].append(
    {"kind": "event", "name": "quarantine", "round": 0, "peer": "evil"}
)
rows = traceview.population_report(timeline)
check(
    rows and rows[0]["actions"] == ["quarantine:evil"],
    "traceview joins quarantine actions into the population row",
)
check("no population_round" not in traceview.render_population(timeline),
      "render_population renders the joined rows")

# Sketch state round-trip: raw-bytes bitset, legacy rebuild lower bound.
state = pop.state_export()
check(
    isinstance(state["coverage"], bytes)
    and len(state["coverage"]) == (512 + 7) // 8,
    "exported coverage is a one-bit-per-client bytes bitset",
)
twin = ClientPopulation.from_state(json.loads(json.dumps({
    k: v for k, v in state.items() if k != "coverage"
})) | {"coverage": state["coverage"]})
check(
    twin.coverage == pop.coverage
    and np.array_equal(twin._coverage, pop._coverage),
    "sketches survive the state round-trip exactly",
)
legacy = {k: v for k, v in state.items() if k != "coverage"}
old = ClientPopulation.from_state(legacy)
check(
    old._sampled_count == old.touched <= pop._sampled_count,
    "legacy checkpoints rebuild coverage as a lower bound",
)

# --- (g) engine attach + fleet gauges + NodeMonitor -------------------------
print("[g] engine registrations, emit_fleet_gauges, NodeMonitor sample")
from tpfl.models import MLP
from tpfl.parallel import FederationEngine
from tpfl.parallel.membership import MembershipView

eng = FederationEngine(MLP(hidden_sizes=(4,)), 4, seed=0, learning_rate=0.1)
view = MembershipView([f"n{i}" for i in range(4)])
eng.attach_membership(view)
eng.attach_population(ClientPopulation(registered=100, sample=4, seed=0))
with fleetobs._meta_lock:
    check(view in fleetobs._views, "attach_membership registered the view")
    check(
        eng.population in fleetobs._populations,
        "attach_population registered the population",
    )
fleetobs.emit_fleet_gauges("drive-fleet")
gf = {
    name
    for (name, labels) in metrics.fold()["gauges"]
    if ("node", "drive-fleet") in labels
}
check(
    {"tpfl_membership_capacity", "tpfl_membership_live", "tpfl_pop_census"} <= gf,
    "emit_fleet_gauges covers membership + population",
)

from tpfl.management.node_monitor import NodeMonitor

NodeMonitor("drive-mon")._sample()
gm = {
    name
    for (name, labels) in metrics.fold()["gauges"]
    if ("node", "drive-mon") in labels
}
check(
    "tpfl_membership_live" in gm and "tpfl_system_cpu_percent" in gm,
    "NodeMonitor samples fleet gauges next to system gauges",
)

# --- (h) metrics lint: suite green + doctored-repo proof --------------------
print("[h] tpflcheck metrics lint")
import subprocess
import sys

proc = subprocess.run(
    [sys.executable, "-m", "tools.tpflcheck"], capture_output=True, text=True
)
check(proc.returncode == 0, "full tpflcheck suite exits 0")

from tools.tpflcheck.metrics import check_metrics

with tempfile.TemporaryDirectory() as d:
    root = pathlib.Path(d)
    (root / "tpfl").mkdir()
    (root / "docs").mkdir()
    (root / "tpfl" / "mod.py").write_text(
        'metrics.counter("tpfl_undocumented_x_total", 1.0)\n'
    )
    (root / "docs" / "observability.md").write_text("# nothing here\n")
    vs = check_metrics(root)
    check(
        len(vs) == 1 and "tpfl_undocumented_x_total" in vs[0].message,
        "lint catches an undocumented tpfl_* registration",
    )
    (root / "docs" / "observability.md").write_text(
        "`tpfl_undocumented_x_total` documented now\n"
    )
    check(not check_metrics(root), "documenting the name clears the lint")

# --- (i) bench fleetobs tier ------------------------------------------------
print("[i] bench fleetobs tier (2-proc determinism, watchdog, overhead, RSS)")
import bench

extra = {}
bench._fleetobs_tier(extra)
fo = extra.get("fleetobs")
check(fo is not None, f"tier produced receipts (err={extra.get('fleetobs_error')})")
for key in (
    "merged_byte_identical",
    "origin_labels_present",
    "watchdog_catch_within_2",
    "uninjected_silent",
    "overhead_within_budget",
):
    check(fo[key] is True, f"bench receipt {key}")
check(fo["pop_sketch"]["rss_bounded"] is True, "pop sketch RSS bounded")
check(fo["pop_sketch"]["bitset_bytes_exact"] is True, "bitset bytes exact")
print(f"  overhead_frac={fo['overhead_frac']:.4f} rounds_per_sec={fo['rounds_per_sec']:.2f}")

print("ALL FLEETOBS DRIVE CHECKS PASSED")
