"""traceview — reconstruct round timelines from tpfl telemetry dumps.

Input: flight-recorder dumps (``flight-<node>-<reason>.json``, written
by ``Node.stop()`` / the chaos harness into
``Settings.TELEMETRY_DUMP_DIR``) and/or in-process span exports
(``tpfl.management.tracing.export()``). Every entry is a span
(``{"kind": "span", "name", "node", "trace", "t0", "t1", ...}``) or an
event (``{"kind": "event", ..., "t"}``); timestamps are
``time.monotonic()`` seconds with a per-process ``wall_anchor`` in the
dump envelope, so dumps from different processes merge onto one
wall-clock axis.

Output: per-trace timelines — for each model payload's 16-byte trace
id, the ordered chain of spans it crossed
(``encode@a → send@a→b → recv@b → decode@b → fold@b``), across every
node that handled it. This is the view no single node ever has: the
gossip hops, retries, breaker trips, chunk streams, decodes and
aggregation folds of one payload, stitched back together.

Run::

    python -m tools.traceview logs/flight-*.json
    python -m tools.traceview --summary dumps/

``--fleet`` switches input to per-node ``MetricsRegistry.dump_json``
documents (``metrics-<node>.json``) and renders ONE labeled-by-node
Prometheus/JSON view of the whole simulation's registries
(:func:`fleet_view` / :func:`render_fleet`; the in-process equivalent
is ``MetricsRegistry.merge``). Paths may also be live ``http(s)://``
endpoints — ``MetricsHTTPServer``'s ``/metrics.json`` (one process) or
rank 0's ``/fleet.json`` (the already-merged cross-host fold) — so the
same command works against a RUNNING federation.

``--population`` is the cross-device cohort view: each
``population_round`` flight event (``ClientPopulation.complete_round``'s
per-round sketch — census coverage, participation fairness, straggler
cutoff) joined with the quarantine engine's ``quarantine`` / ``readmit``
verdicts for that round (:func:`population_report`).

``--ledger`` joins the learning-plane ledger's ``contrib`` / ``anomaly``
events (``tpfl.management.ledger``, recorded into the same flight rings
when ``Settings.LEDGER_ENABLED``) with the hop timelines by trace id:
one command answers "which peer's update was this payload, what were
its statistics, and was it flagged" — the update's network journey and
its learning-plane verdict on one line.

Pure functions (:func:`build_timeline`, :func:`hop_path`,
:func:`ledger_report`) are the test/bench surface; the CLI is a thin
formatter over them.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Any, Iterable


def load(paths: Iterable[str]) -> list[dict]:
    """Load spans/events from dump files (or directories of them).

    Accepts flight-recorder dump envelopes (``{"node", "reason",
    "wall_anchor", "events": [...]}``) and bare JSON lists of entries.
    Each entry gains a wall-clock timestamp (``wt``) from its dump's
    anchor so cross-process entries order correctly."""
    entries: list[dict] = []
    files: list[pathlib.Path] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            files.extend(sorted(path.glob("flight-*.json")))
        else:
            files.append(path)
    for path in files:
        doc = json.loads(path.read_text(encoding="utf-8"))
        if isinstance(doc, dict):
            anchor = float(doc.get("wall_anchor", 0.0))
            batch = doc.get("events", [])
        else:
            anchor, batch = 0.0, doc
        for e in batch:
            e = dict(e)
            e["wt"] = anchor + float(e.get("t0", e.get("t", 0.0)))
            entries.append(e)
    return entries


def _stamp(e: dict) -> float:
    return float(e.get("wt", e.get("t0", e.get("t", 0.0))))


def build_timeline(entries: Iterable[dict]) -> dict[str, list[dict]]:
    """Group spans/events by trace id, each trace's entries in time
    order. Entries without a trace id (stage spans, system events) are
    grouped under ``""`` — the per-node backbone the payload traces
    hang between. Duplicate spans are dropped by span id: a node that
    dumped twice (crash dump, then its stop dump) contributes each
    span once."""
    timeline: dict[str, list[dict]] = {}
    seen: set = set()
    for e in entries:
        # Span ids are unique per node; events dedup on their full
        # identity (identical copies across overlapping dumps).
        sid = e.get("span")
        key = (
            (e.get("node"), sid)
            if sid is not None
            else (e.get("node"), e.get("name"), e.get("trace"), e.get("t"))
        )
        if key in seen:
            continue
        seen.add(key)
        timeline.setdefault(str(e.get("trace", "")), []).append(dict(e))
    for chain in timeline.values():
        chain.sort(key=_stamp)
    return timeline


def hop_path(chain: list[dict]) -> list[str]:
    """A trace's condensed hop chain: ``op@node`` (send shows the
    peer: ``send@a->b``), retries/events included in order."""
    out: list[str] = []
    for e in chain:
        name, node = str(e.get("name", "?")), str(e.get("node", "?"))
        if name in ("send", "retry") and e.get("peer"):
            out.append(f"{name}@{node}->{e['peer']}")
        else:
            out.append(f"{name}@{node}")
    return out


def trace_complete(chain: list[dict]) -> bool:
    """A payload trace is reconstructable end-to-end when it shows the
    encode AND a consuming hop (decode or fold) — on a different node
    unless the federation is single-node."""
    names = {str(e.get("name", "")) for e in chain}
    if "encode" not in names:
        return False
    if not ({"decode", "fold"} & names):
        return False
    encode_nodes = {
        e.get("node") for e in chain if e.get("name") == "encode"
    }
    consume_nodes = {
        e.get("node") for e in chain if e.get("name") in ("decode", "fold")
    }
    return bool(consume_nodes - encode_nodes) or encode_nodes == consume_nodes


def ledger_report(timeline: dict[str, list[dict]]) -> list[dict]:
    """Join learning-plane ledger entries with their hop timelines.

    For every ``contrib`` event (one accepted contribution's on-device
    stats, recorded by ``tpfl.management.ledger``) returns::

        {"trace", "peer", "observer", "round", "update_norm",
         "cos_ref", "num_samples", "flagged", "reasons", "hops"}

    ``hops`` is the payload's condensed hop chain (``encode@a →
    send@a->b → ... → fold@b``) when the contribution's trace id is
    reconstructable (tracing was on), else ``[]`` — a locally-fitted
    contribution has no wire journey. ``anomaly`` events merge into
    their contribution's row (reasons/z), and the quarantine engine's
    ``quarantine`` / ``readmit`` actions (tpfl.management.quarantine)
    merge as the row's ``action`` — the payload's network journey, its
    learning-plane verdict, AND the defense decision it triggered on
    one line; untraceable ledger rows sort last."""
    ledger_names = ("contrib", "anomaly", "quarantine", "readmit")
    rows: dict[tuple, dict] = {}
    for trace, chain in timeline.items():
        hops = [e for e in chain if e.get("name") not in ledger_names]
        for e in chain:
            if e.get("name") != "contrib":
                continue
            key = (str(e.get("node", "")), str(e.get("peer", "")),
                   int(e.get("round", -1)))
            rows[key] = {
                "trace": trace,
                "peer": str(e.get("peer", "")),
                "observer": str(e.get("node", "")),
                "round": int(e.get("round", -1)),
                "update_norm": float(e.get("update_norm", 0.0)),
                "cos_ref": float(e.get("cos_ref", 0.0)),
                "num_samples": int(e.get("num_samples", 0)),
                "flagged": bool(e.get("flagged", False)),
                "reasons": [],
                "hops": hop_path(hops) if trace else [],
            }
        for e in chain:
            name = e.get("name")
            if name not in ("anomaly", "quarantine", "readmit"):
                continue
            key = (str(e.get("node", "")), str(e.get("peer", "")),
                   int(e.get("round", -1)))
            row = rows.get(key)
            if row is None:
                if name == "anomaly":
                    continue
                # Quarantine actions can outlive their triggering
                # contribution's ring entry: surface them standalone.
                row = rows[key] = {
                    "trace": trace,
                    "peer": str(e.get("peer", "")),
                    "observer": str(e.get("node", "")),
                    "round": int(e.get("round", -1)),
                    "update_norm": 0.0,
                    "cos_ref": 0.0,
                    "num_samples": 0,
                    "flagged": False,
                    "reasons": [],
                    "hops": hop_path(hops) if trace else [],
                }
            if name == "anomaly":
                row["flagged"] = True
                row["reasons"] = [
                    r for r in str(e.get("reasons", "")).split(",") if r
                ]
                if "z_norm" in e:
                    row["z_norm"] = float(e["z_norm"])
            else:
                row["action"] = name
                if name == "quarantine":
                    row["flagged"] = True
                    if not row["reasons"]:
                        row["reasons"] = [
                            r
                            for r in str(e.get("reasons", "")).split(",")
                            if r
                        ]
    return sorted(
        rows.values(),
        key=lambda r: (r["round"], r["peer"], r["observer"]),
    )


def render_ledger(timeline: dict[str, list[dict]]) -> str:
    rows = ledger_report(timeline)
    if not rows:
        return "no ledger entries (was Settings.LEDGER_ENABLED on?)"
    lines = [
        f"{len(rows)} ledger entries "
        f"({sum(1 for r in rows if r['flagged'])} flagged)",
        f"{'rnd':>3} {'peer':<18} {'observer':<18} {'|update|':>10} "
        f"{'cos_ref':>8}  flags",
    ]
    for r in rows:
        mark = ",".join(r["reasons"]) if r["reasons"] else (
            "FLAGGED" if r["flagged"] else "-"
        )
        if r.get("action"):
            mark = f"{mark} [{r['action'].upper()}]"
        lines.append(
            f"{r['round']:>3} {r['peer']:<18} {r['observer']:<18} "
            f"{r['update_norm']:>10.4g} {r['cos_ref']:>8.3f}  {mark}"
        )
        if r["hops"]:
            lines.append(f"      hops: {' -> '.join(r['hops'])}")
    return "\n".join(lines)


def load_metric_dumps(paths: Iterable[str]) -> dict[str, dict]:
    """Load per-node ``MetricsRegistry.dump_json`` documents for the
    fleet view: files (or directories of ``metrics-*.json``) keyed by
    node name — the ``metrics-`` / ``.json`` trimmed file stem.

    ``http(s)://`` paths scrape a LIVE endpoint instead
    (``MetricsHTTPServer`` — ``/metrics.json`` for one process,
    ``/fleet.json`` for rank 0's already-merged cross-host view), so
    ``--fleet`` works against a running federation, not just its
    post-mortem dumps. Live documents key by host:port."""
    docs: dict[str, dict] = {}
    files: list[pathlib.Path] = []
    for p in paths:
        if str(p).startswith(("http://", "https://")):
            import urllib.parse
            import urllib.request

            with urllib.request.urlopen(str(p), timeout=10) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
            docs[urllib.parse.urlparse(str(p)).netloc or str(p)] = doc
            continue
        path = pathlib.Path(p)
        if path.is_dir():
            files.extend(sorted(path.glob("metrics-*.json")))
        else:
            files.append(path)
    for path in files:
        name = path.stem
        if name.startswith("metrics-"):
            name = name[len("metrics-"):]
        docs[name] = json.loads(path.read_text(encoding="utf-8"))
    return docs


def _with_origin(series: str, origin: str) -> str:
    if series.endswith("}"):
        return f"{series[:-1]},origin={origin}}}"
    return f"{series}{{origin={origin}}}"


def fleet_view(docs: dict[str, dict]) -> dict[str, Any]:
    """Merge per-node metrics dumps into ONE labeled-by-node view —
    today each node's registry scrapes in isolation; this is the whole
    simulation on one axis. Every series gains an ``origin=<node>``
    label (the in-process equivalent is
    ``MetricsRegistry.merge(*regs, names=...)``); series strings keep
    the ``name{k=v,...}`` JSON-dump format."""
    out: dict[str, Any] = {
        "nodes": sorted(docs),
        "counters": {},
        "gauges": {},
        "histograms": {},
    }
    for name in sorted(docs):
        doc = docs[name]
        for kind in ("counters", "gauges"):
            for series, v in sorted((doc.get(kind) or {}).items()):
                out[kind][_with_origin(series, name)] = v
        for series, h in sorted((doc.get("histograms") or {}).items()):
            out["histograms"][_with_origin(series, name)] = h
    return out


def render_fleet(view: dict[str, Any]) -> str:
    """Prometheus-flavored text of a :func:`fleet_view` (histograms
    condense to their ``_sum`` / ``_count`` series — the merged view is
    for reading across nodes, not for re-scraping)."""
    lines = [
        f"# fleet view: {len(view['nodes'])} nodes: "
        f"{', '.join(view['nodes'])}"
    ]
    for series in sorted(view["counters"]):
        lines.append(f"{series} {view['counters'][series]:g}")
    for series in sorted(view["gauges"]):
        lines.append(f"{series} {view['gauges'][series]:g}")
    for series in sorted(view["histograms"]):
        h = view["histograms"][series]
        name, _, labels = series.partition("{")
        labels = "{" + labels if labels else ""
        lines.append(f"{name}_sum{labels} {h.get('sum', 0):g}")
        lines.append(f"{name}_count{labels} {h.get('count', 0)}")
    return "\n".join(lines) + "\n"


def population_report(timeline: dict[str, list[dict]]) -> list[dict]:
    """Cohort health per population round, joined with the defense
    plane: every ``population_round`` flight event (the cross-device
    observatory's per-round sketch — census/coverage/fairness/
    stragglers, recorded by ``ClientPopulation.complete_round``)
    becomes one row, and any ``quarantine`` / ``readmit`` actions the
    quarantine engine took in the same round merge into it — "how
    healthy was this round's cohort, and what did the defense do about
    it" on one line."""
    rounds: dict[int, dict] = {}
    actions: dict[int, list[str]] = {}
    for chain in timeline.values():
        for e in chain:
            name = e.get("name")
            if name == "population_round":
                r = int(e.get("round", -1))
                rounds[r] = {
                    "round": r,
                    "census": int(e.get("census", 0)),
                    "sampled": int(e.get("sampled", 0)),
                    "folded": int(e.get("folded", 0)),
                    "cut": int(e.get("cut", 0)),
                    "touched": int(e.get("touched", 0)),
                    "coverage": float(e.get("coverage", 0.0)),
                    "fairness": float(e.get("fairness", 0.0)),
                    "actions": [],
                }
            elif name in ("quarantine", "readmit"):
                r = int(e.get("round", -1))
                actions.setdefault(r, []).append(
                    f"{name}:{e.get('peer', '?')}"
                )
    for r, acts in actions.items():
        if r in rounds:
            rounds[r]["actions"] = sorted(acts)
    return [rounds[r] for r in sorted(rounds)]


def render_population(timeline: dict[str, list[dict]]) -> str:
    rows = population_report(timeline)
    if not rows:
        return (
            "no population_round events (is a ClientPopulation "
            "attached and completing rounds?)"
        )
    lines = [
        f"{len(rows)} population rounds "
        f"(census {rows[-1]['census']}, "
        f"coverage {rows[-1]['coverage']:.4f}, "
        f"touched {rows[-1]['touched']})",
        f"{'rnd':>4} {'sampled':>7} {'folded':>6} {'cut':>4} "
        f"{'touched':>7} {'coverage':>8} {'fairness':>8}  defense",
    ]
    for r in rows:
        acts = ", ".join(r["actions"]) if r["actions"] else "-"
        lines.append(
            f"{r['round']:>4} {r['sampled']:>7} {r['folded']:>6} "
            f"{r['cut']:>4} {r['touched']:>7} {r['coverage']:>8.4f} "
            f"{r['fairness']:>8.4f}  {acts}"
        )
    return "\n".join(lines)


def summarize(timeline: dict[str, list[dict]]) -> dict[str, Any]:
    traced = {t: c for t, c in timeline.items() if t}
    complete = {t: c for t, c in traced.items() if trace_complete(c)}
    nodes = sorted(
        {str(e.get("node", "?")) for c in timeline.values() for e in c}
    )
    return {
        "traces": len(traced),
        "complete_traces": len(complete),
        "nodes": nodes,
        "entries": sum(len(c) for c in timeline.values()),
    }


def render(timeline: dict[str, list[dict]], limit: int = 0) -> str:
    lines: list[str] = []
    s = summarize(timeline)
    lines.append(
        f"{s['entries']} entries, {s['traces']} payload traces "
        f"({s['complete_traces']} complete) across {len(s['nodes'])} "
        f"nodes: {', '.join(s['nodes'])}"
    )
    shown = 0
    for trace in sorted(t for t in timeline if t):
        chain = timeline[trace]
        if limit and shown >= limit:
            lines.append(f"... ({s['traces'] - shown} more traces)")
            break
        shown += 1
        t0 = _stamp(chain[0])
        mark = "✓" if trace_complete(chain) else "…"
        lines.append(f"\ntrace {trace[:16]} {mark}")
        for e in chain:
            dt = _stamp(e) - t0
            name, node = str(e.get("name", "?")), str(e.get("node", "?"))
            dur = ""
            if "t1" in e and "t0" in e:
                dur = f"  ({(float(e['t1']) - float(e['t0'])) * 1e3:.2f} ms)"
            peer = f" -> {e['peer']}" if e.get("peer") else ""
            err = f"  ERROR {e['error']}" if e.get("error") else ""
            lines.append(
                f"  +{dt * 1e3:9.2f} ms  {name:<12} {node}{peer}{dur}{err}"
            )
    return "\n".join(lines)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        description="Reconstruct tpfl round timelines from telemetry dumps"
    )
    ap.add_argument("paths", nargs="+", help="dump files or directories")
    ap.add_argument(
        "--summary", action="store_true",
        help="counts only (no per-trace chains)",
    )
    ap.add_argument(
        "--ledger", action="store_true",
        help="learning-plane view: contribution stats + anomaly flags "
        "joined with each payload's hop chain by trace id",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="fleet metrics view: merge per-node MetricsRegistry JSON "
        "dumps (metrics-<node>.json) into one labeled-by-node "
        "Prometheus text (--summary: the merged JSON document)",
    )
    ap.add_argument(
        "--population", action="store_true",
        help="population-plane view: per-round cohort health "
        "(coverage/fairness/stragglers from population_round events) "
        "joined with quarantine/readmit verdicts",
    )
    ap.add_argument(
        "--limit", type=int, default=20,
        help="max traces to render (0 = all)",
    )
    args = ap.parse_args(argv)
    if args.fleet:
        view = fleet_view(load_metric_dumps(args.paths))
        if args.summary:
            print(json.dumps(view, indent=2, sort_keys=True))
        else:
            print(render_fleet(view), end="")
        return 0
    timeline = build_timeline(load(args.paths))
    if args.population:
        print(render_population(timeline))
    elif args.ledger:
        print(render_ledger(timeline))
    elif args.summary:
        print(json.dumps(summarize(timeline), indent=2))
    else:
        print(render(timeline, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
