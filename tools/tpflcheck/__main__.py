"""``python -m tools.tpflcheck`` — run the full suite, exit 1 on any
unwaived violation. ``-v`` also prints waived findings and the static
lock-order edge list (the input to docs/concurrency.md's canonical
order)."""

from __future__ import annotations

import sys
import time

from tools.tpflcheck import lock_edges, run_all


def main(argv: "list[str] | None" = None) -> int:
    args = sys.argv[1:] if argv is None else argv
    verbose = "-v" in args or "--verbose" in args
    t0 = time.monotonic()
    violations, waived, warnings, _ = run_all()
    elapsed = time.monotonic() - t0

    for v in violations:
        print(v.render(), file=sys.stderr)
    if verbose:
        for w in waived:
            print(f"waived: {w}")
        print("\nstatic lock-order edges:")
        seen = set()
        for e in lock_edges():
            key = (e.src, e.dst)
            if key in seen:
                continue
            seen.add(key)
            via = f" via {e.via}" if e.via else ""
            print(f"  {e.src} -> {e.dst}  ({e.file}:{e.line}{via})")
    for w in warnings:
        print(f"warning: {w}")

    if violations:
        print(
            f"tpflcheck FAILED — {len(violations)} violation(s), "
            f"{len(waived)} waived ({elapsed:.2f}s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"tpflcheck OK — all checks passed, {len(waived)} waived "
        f"finding(s), {len(warnings)} warning(s) ({elapsed:.2f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
