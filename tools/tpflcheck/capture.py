"""Trace-capture totality lint: what a compiled program captures must
be an axis of its cache key.

JAX traces a Python callable ONCE per cache key and replays the
compiled XLA program forever after. Anything the traced body reads
from ambient Python state — a ``Settings.<KNOB>``, a module global —
is baked into the program as a constant at trace time. If that value
is not an axis of the cache key the program is stored under, flipping
the knob later silently serves a STALE program: no error, no recompile,
just last month's semantics. This is the repo's worst recurring bug
class (the PR-13 cache keys over ``ENGINE_TELEMETRY`` /
``ENGINE_WIRE_CODEC`` / ``WIRE_TOPK_FRAC`` / ``ENGINE_DONATE`` were
kept total by reviewer discipline alone); this pass makes it a
machine-checked contract.

Three rules over ``tpfl/``:

1. **Trace purity** — no ``Settings.<KNOB>`` read inside a traced
   region. Traced regions are: functions jitted directly
   (``@jax.jit`` / ``@partial(jax.jit, ...)`` decorations, ``jax.jit(f)``
   on a module/local function), every function nested inside a program
   BUILDER (a ``_build_*`` / ``_make_*`` / ``build_*`` / ``make_*``
   function in a jax-importing module — the nested defs ARE the traced
   program body), and — one level deep, like ``locks.py`` — any
   same-module function or ``self.`` method a traced region calls.
   Knob values must enter as builder arguments (key axes) or traced
   inputs. Escape hatch: ``# trace-static: <reason>`` on the read's
   line (or the comment block above) for values that are genuinely
   trace-constant by design.

2. **Key totality** (getter side) — in any function that builds a
   cache key (``key = (<tuple>)``) and uses it against a program cache
   (``cache.get(key)`` / ``cache[key]``, or ``key`` handed to a shared
   lookup helper), every non-self parameter must appear inside the key
   tuple — a parameter that selects or parameterizes the build but is
   missing from the key is exactly one forgotten axis. Parameters that
   are runtime INPUTS (passed to the cache-fetched callable when it is
   invoked in the same scope) are exempt. Free local names captured by
   a builder lambda/closure handed along with the key must appear in
   the key too (the ``_shared_program`` discipline).

3. **Knob→key flow** (dispatch side) — in a function that resolves
   Settings knobs into locals (directly, or by tuple-unpacking a
   same-class helper that reads Settings — ``_resolve_variant``) AND
   calls a key-building getter from rule 2, every knob-derived local
   must appear among some getter call's arguments. A resolved knob
   that never reaches the key means dispatch ignores the live value.

Waiver keys: ``capture:<file>::<qualname>::<name>`` (rule 2/3) and
``capture:<file>:<line>`` (rule 1). The runtime complement is
``Settings.TRACE_CONTRACTS`` (``tpfl.concurrency.check_contract``):
the engine stamps every cached program with the knob values its key
was built from and re-checks them live at dispatch, so a key-hygiene
bug that slips past the static pass fails loudly with a named witness
instead of silently serving stale semantics.
"""

from __future__ import annotations

import ast
import pathlib
import re

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

_BUILDER_RE = re.compile(r"^_?(?:build|make)_")
_ANNOT_RE = re.compile(r"#\s*trace-static:\s*(\S.*)$")

#: Modules whose builders are host-side object factories, not program
#: builders (no jax import => no traced regions).
_JAX_MODULES_HINT = ("jax", "jnp", "lax", "optax", "flax")

#: The program-cache seams (rules 2/3): modules whose ``key = (...)``
#: + cache-lookup functions select COMPILED PROGRAMS. Other keyed
#: stores (metric registries, model caches) key data, not traces —
#: a missing axis there is a logic bug, not a stale program.
CACHE_MODULES = (
    "tpfl/parallel/engine.py",
    "tpfl/parallel/federation.py",
    "tpfl/parallel/federation_learner.py",
    "tpfl/parallel/sharded.py",
    "tpfl/learning/jax_learner.py",
    "tpfl/learning/compression.py",
    "tpfl/simulation/batched_fit.py",
)


def _annotated(lines: list[str], lineno: int) -> bool:
    """``# trace-static: <reason>`` on the line or the contiguous
    comment block directly above (guards.py's annotation discipline)."""
    candidates = [lines[lineno - 1]]
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        candidates.append(lines[i])
        i -= 1
    return any(_ANNOT_RE.search(text) for text in candidates)


def _imports_jax(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] in _JAX_MODULES_HINT for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] in _JAX_MODULES_HINT:
                return True
    return False


def _is_jax_jit(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_decorated(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if _is_jax_jit(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return True
            if (
                isinstance(dec.func, ast.Name)
                and dec.func.id == "partial"
                and dec.args
                and _is_jax_jit(dec.args[0])
            ):
                return True
    return False


def _settings_reads(node: ast.AST) -> "list[tuple[str, int]]":
    """(knob, line) for every ``Settings.<KNOB>`` read under ``node``."""
    out = []
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "Settings"
            and sub.attr.isupper()
        ):
            out.append((sub.attr, sub.lineno))
    return out


class _FunctionIndex:
    """Same-module function/method defs for one-level call resolution."""

    def __init__(self, tree: ast.Module) -> None:
        self.module_fns: dict[str, ast.AST] = {}
        self.methods: dict[tuple[str, str], ast.AST] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_fns[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.methods[(node.name, sub.name)] = sub

    def resolve(self, call: ast.Call, cls: "str | None") -> "ast.AST | None":
        fn = call.func
        if isinstance(fn, ast.Name):
            return self.module_fns.get(fn.id)
        if (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id in ("self", "cls")
            and cls is not None
        ):
            return self.methods.get((cls, fn.attr))
        return None


def _traced_roots(tree: ast.Module) -> "list[ast.AST]":
    """Function nodes whose bodies run under trace: directly-jitted
    defs/lambdas, and every def nested inside a program builder."""
    roots: list[ast.AST] = []
    index = _FunctionIndex(tree)
    jitted_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decorated(node):
                roots.append(node)
            elif _BUILDER_RE.match(node.name):
                # The builder's nested defs are the program body; the
                # builder's own top level is host code (it runs once,
                # at build time — but anything it bakes into the
                # closure the nested defs read IS part of the trace).
                for sub in ast.walk(node):
                    if sub is not node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                    ):
                        roots.append(sub)
        elif isinstance(node, ast.Call) and _is_jax_jit(node.func):
            if node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Lambda):
                    roots.append(arg)
                elif isinstance(arg, ast.Name):
                    jitted_names.add(arg.id)
    for name in jitted_names:
        fn = index.module_fns.get(name)
        if fn is not None:
            roots.append(fn)
    return roots


def _check_purity(
    r: str, tree: ast.Module, lines: list[str]
) -> list[Violation]:
    index = _FunctionIndex(tree)
    # Map every function node to its enclosing class for self-resolution.
    enclosing_cls: dict[ast.AST, "str | None"] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    enclosing_cls[sub] = node.name

    seen: set[int] = set()
    worklist: list[tuple[ast.AST, int]] = [(n, 0) for n in _traced_roots(tree)]
    violations: list[Violation] = []
    while worklist:
        fn, depth = worklist.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for knob, lineno in _settings_reads(fn):
            if _annotated(lines, lineno):
                continue
            violations.append(
                Violation(
                    "capture", r, lineno,
                    f"Settings.{knob} read inside a traced program body — "
                    "the value is baked in at trace time and a later knob "
                    "flip silently serves a stale compiled program; pass "
                    "it in as a cache-key axis / traced input, or annotate "
                    "'# trace-static: <reason>'",
                    f"capture:{r}:{lineno}",
                )
            )
        if depth >= 1:
            continue  # one level of call resolution, like locks.py
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call):
                callee = index.resolve(sub, enclosing_cls.get(fn))
                if callee is not None:
                    worklist.append((callee, depth + 1))
    # Dedupe (a nested def reachable from two roots reports once).
    uniq: dict[tuple[str, int], Violation] = {}
    for v in violations:
        uniq.setdefault((v.key, v.line), v)
    return list(uniq.values())


# --- rule 2/3: cache-key totality and knob→key flow ----------------------


def _names_in(node: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    }


class _Getter:
    """A key-building cache-getter function: where its key tuple is,
    which params it has, and which it keys / feeds to the cached fn."""

    def __init__(self, fn: ast.AST, cls: "str | None") -> None:
        self.fn = fn
        self.cls = cls
        self.name = fn.name
        args = fn.args
        self.params = [
            a.arg
            for a in (args.posonlyargs + args.args + args.kwonlyargs)
            if a.arg not in ("self", "cls")
        ]
        self.key_tuple: "ast.Tuple | None" = None
        self.key_line = fn.lineno
        self.cache_hit = False  # key used against a dict / passed on
        self.fetched_names: set[str] = set()  # locals bound from cache
        self._scan()

    def _scan(self) -> None:
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id == "key"
                    and isinstance(val, ast.Tuple)
                ):
                    self.key_tuple = val
                    self.key_line = node.lineno
                # fn = cache.get(key) / fn = cache[key] / chained assign
                if isinstance(tgt, ast.Name) and _uses_key(val):
                    self.fetched_names.add(tgt.id)
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and any(
                        isinstance(a, ast.Name) and a.id == "key"
                        for a in node.args
                    )
                ):
                    self.cache_hit = True
                elif any(
                    isinstance(a, ast.Name) and a.id == "key"
                    for a in node.args
                ):
                    self.cache_hit = True  # key handed to a lookup helper
            if isinstance(node, ast.Subscript):
                sl = node.slice
                if isinstance(sl, ast.Name) and sl.id == "key":
                    self.cache_hit = True

    @property
    def is_getter(self) -> bool:
        return self.key_tuple is not None and self.cache_hit

    def runtime_input_names(self) -> set[str]:
        """Names passed to the cache-fetched callable when invoked in
        this scope — runtime inputs, not key axes."""
        out: set[str] = set()
        for node in ast.walk(self.fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in self.fetched_names
            ):
                for a in list(node.args) + [k.value for k in node.keywords]:
                    out |= _names_in(a)
        return out

    def closure_arg_names(self) -> "list[tuple[set[str], int]]":
        """Free names of lambdas/defs passed alongside ``key`` in a
        call (the ``_shared_program(key, lambda: ...)`` shape)."""
        out: list[tuple[set[str], int]] = []
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Call):
                continue
            has_key = any(
                isinstance(a, ast.Name) and a.id == "key" for a in node.args
            )
            if not has_key:
                continue
            for a in node.args:
                if isinstance(a, ast.Lambda):
                    out.append((_names_in(a.body), a.lineno))
        return out


def _uses_key(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript):
            if isinstance(sub.slice, ast.Name) and sub.slice.id == "key":
                return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if sub.func.attr == "get" and any(
                isinstance(a, ast.Name) and a.id == "key" for a in sub.args
            ):
                return True
    return False


def _collect_getters(tree: ast.Module) -> "list[_Getter]":
    getters: list[_Getter] = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            g = _Getter(node, None)
            if g.is_getter or g.key_tuple is not None:
                getters.append(g)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    g = _Getter(sub, node.name)
                    if g.is_getter or g.key_tuple is not None:
                        getters.append(g)
    return getters


def _check_key_totality(
    r: str, getters: "list[_Getter]", lines: list[str]
) -> list[Violation]:
    violations: list[Violation] = []
    for g in getters:
        if g.key_tuple is None:
            continue
        key_names = _names_in(g.key_tuple)
        runtime = g.runtime_input_names() if g.is_getter else set()
        qual = f"{g.cls}.{g.name}" if g.cls else g.name
        if g.is_getter:
            for p in g.params:
                if p in key_names or p in runtime:
                    continue
                if _annotated(lines, g.key_line):
                    continue
                violations.append(
                    Violation(
                        "capture", r, g.key_line,
                        f"parameter `{p}` of cache getter {qual}() is not "
                        "an axis of its program-cache key — a variant it "
                        "selects will silently collide with another "
                        "variant's compiled program; add it to the key "
                        "tuple (or annotate '# trace-static: <reason>' "
                        "on the key line)",
                        f"capture:{r}::{qual}::{p}",
                    )
                )
        # Closure-capture totality: _shared_program(key, lambda: ...)
        for free, lineno in g.closure_arg_names():
            local_free = free & _local_bindings(g.fn)
            for name in sorted(local_free - key_names):
                if _annotated(lines, lineno):
                    continue
                violations.append(
                    Violation(
                        "capture", r, lineno,
                        f"builder closure in {qual}() captures local "
                        f"`{name}` which is not an axis of the cache key "
                        "it is stored under — two configs differing only "
                        f"in `{name}` would share one compiled program",
                        f"capture:{r}::{qual}::{name}",
                    )
                )
    return violations


def _local_bindings(fn: ast.AST) -> set[str]:
    """Parameter and assigned-local names of ``fn`` (its own scope
    only — nested defs are their own scope)."""
    args = fn.args
    out = {
        a.arg
        for a in (args.posonlyargs + args.args + args.kwonlyargs)
        if a.arg not in ("self", "cls")
    }

    def visit(node: ast.AST, top: bool = False) -> None:
        if not top and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store,)
        ):
            out.add(node.id)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(fn, top=True)
    return out


def _check_knob_flow(
    r: str,
    tree: ast.Module,
    getters: "list[_Getter]",
    lines: list[str],
) -> list[Violation]:
    """Rule 3: Settings-derived locals must reach a getter's args."""
    # Only getters with keyed parameters can receive a knob axis —
    # a zero-arg builder (`_build_train_epoch`) takes no variant
    # selectors, so dispatching through it creates no flow obligation.
    strict_getter_names = {
        (g.cls, g.name) for g in getters if g.is_getter and g.params
    }
    if not strict_getter_names:
        return []
    # Same-class helpers whose bodies read Settings (one level): their
    # call results count as knob-derived ("_resolve_variant").
    knob_helpers: dict[tuple["str | None", str], list[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    reads = [k for k, _ in _settings_reads(sub)]
                    if reads:
                        knob_helpers[(node.name, sub.name)] = reads

    violations: list[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for fn in node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # knob-derived locals: name -> (knob(s), line)
            derived: dict[str, tuple[str, int]] = {}
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                    continue
                tgt, val = stmt.targets[0], stmt.value
                reads = [k for k, _ in _settings_reads(val)]
                if (
                    not reads
                    and isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Attribute)
                    and isinstance(val.func.value, ast.Name)
                    and val.func.value.id in ("self", "cls")
                ):
                    reads = knob_helpers.get(
                        (node.name, val.func.attr), []
                    )
                if not reads:
                    continue
                label = "/".join(sorted(set(reads)))
                if isinstance(tgt, ast.Name):
                    derived[tgt.id] = (label, stmt.lineno)
                elif isinstance(tgt, ast.Tuple):
                    for elt in tgt.elts:
                        if isinstance(elt, ast.Name):
                            derived[elt.id] = (label, stmt.lineno)
            if not derived:
                continue
            # getter calls in this fn (self.<getter> / bare <getter>)
            getter_arg_names: set[str] = set()
            calls_getter = False
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                f = call.func
                name = None
                if isinstance(f, ast.Name):
                    name = f.id
                elif isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name
                ) and f.value.id in ("self", "cls"):
                    name = f.attr
                if name is None:
                    continue
                if any(n == name for _c, n in strict_getter_names):
                    calls_getter = True
                    for a in list(call.args) + [
                        k.value for k in call.keywords
                    ]:
                        getter_arg_names |= _names_in(a)
            if not calls_getter:
                continue
            qual = f"{node.name}.{fn.name}"
            for name, (label, lineno) in sorted(derived.items()):
                if name in getter_arg_names:
                    continue
                if _annotated(lines, lineno):
                    continue
                violations.append(
                    Violation(
                        "capture", r, lineno,
                        f"{qual}() resolves Settings ({label}) into "
                        f"`{name}` but never passes it to the program "
                        "cache getter it dispatches through — the live "
                        "knob value cannot select the program variant; "
                        "thread it into the key (or annotate "
                        "'# trace-static: <reason>')",
                        f"capture:{r}::{qual}::{name}",
                    )
                )
    return violations


def check_capture(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    violations: list[Violation] = []
    for path in py_files(root):
        r = rel(root, path)
        try:
            src = core.source(path)
            tree = core.parse(path)
        except SyntaxError:
            continue
        lines = src.splitlines()
        if _imports_jax(tree):
            violations += _check_purity(r, tree, lines)
        if r in CACHE_MODULES:
            getters = _collect_getters(tree)
            violations += _check_key_totality(r, getters, lines)
            violations += _check_knob_flow(r, tree, getters, lines)
    return violations
