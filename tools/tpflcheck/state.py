"""Checkpoint-state totality lint.

Five subsystems round-trip state through ``export_state`` /
``import_state`` (engine) or ``state_export`` / ``state_import``
(controller, membership, population, quarantine), and the window
pipeline's cadence bookkeeping rides the engine snapshot. A field
added to one of these classes and NOT added to its export is silent
state loss: kill-and-resume "works" and quietly resumes from a
different point (the exact bug class pfl-research calls out — see
PAPERS.md). This pass makes export totality a review-time failure:

1. **Field totality** — every mutable field of a roster class
   (assigned in ``__init__`` or an ``attach_*`` method AND re-assigned
   / mutated anywhere outside ``__init__`` — construction-time config
   the constructor rebuilds is exempt) must either be READ by that
   class's export method (resolved one call level deep into same-class
   helpers), or carry ``# ephemeral: <reason>`` on the declaring
   assignment (or the contiguous comment block above it). Classes
   without an export method (``WindowPipeline``, ``WindowPrefetcher``
   — their durable cadence state rides the ENGINE's snapshot) must
   annotate every such field.
2. **Key symmetry** — every snapshot key the export method writes
   (subscript stores and returned/assigned dict-literal keys, one call
   level deep) must be consumed by the import method (subscript loads,
   ``.get``, ``in`` tests against the state parameter, one call level
   deep), and vice versa. An export-only key is dead weight the resume
   silently drops (the historical ``seed`` bug this pass found — see
   pyproject.toml); an import-only key can never arrive.

Annotation grammar: ``# ephemeral: <reason>`` — reason mandatory
(program caches, derived masks, live thread handles, runtime bindings
re-established on restore).

Runtime half: ``Settings.STATE_CONTRACTS``
(:class:`tpfl.management.checkpoint.EngineCheckpointer`) — every save
immediately re-loads its own serialized snapshot onto a shadow import
and compares per-key digests, raising ``StateContractError`` with a
named-field witness. Static totality at review time; the shadow
round-trip catches what static analysis cannot (a field whose VALUE
does not survive msgpack).

Waiver keys: ``state:<file>::<Class>.<attr>`` (totality),
``state:<file>::<Class>[<key>]:export-only`` / ``:import-only``
(symmetry).
"""

from __future__ import annotations

import ast
import pathlib
import re

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, repo_root

#: The checkpointed roster: file -> classes whose state round-trips
#: (or, for the pipeline classes, rides the engine snapshot).
ROSTER: "tuple[tuple[str, tuple[str, ...]], ...]" = (
    ("tpfl/parallel/engine.py", ("FederationEngine",)),
    ("tpfl/parallel/membership.py", ("MembershipView",)),
    ("tpfl/parallel/population.py", ("ClientPopulation",)),
    ("tpfl/learning/async_control.py", ("AsyncController",)),
    ("tpfl/management/quarantine.py", ("QuarantineEngine",)),
    ("tpfl/parallel/window_pipeline.py", ("WindowPipeline", "WindowPrefetcher")),
)

_EXPORT_NAMES = ("export_state", "state_export")
_IMPORT_NAMES = ("import_state", "state_import")

_EPHEMERAL_RE = re.compile(r"#\s*ephemeral:\s*(\S.*)?$")

#: Method calls that mutate a container in place — a field touched
#: only through these still carries runtime state the resume needs.
_MUTATOR_CALLS = {
    "append", "extend", "add", "update", "clear", "pop", "popitem",
    "setdefault", "remove", "discard", "insert", "appendleft",
}


def _ephemeral_reason(lines: "list[str]", lineno: int) -> "str | None | bool":
    """``# ephemeral:`` lookup on the line or the contiguous comment
    block above. Returns the reason string, ``""`` for an annotation
    missing its reason, or False when unannotated."""
    candidates = [lines[lineno - 1]]
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        candidates.append(lines[i])
        i -= 1
    for text in candidates:
        m = _EPHEMERAL_RE.search(text)
        if m:
            return (m.group(1) or "").strip()
    return False


def _self_attr(node: ast.AST) -> "str | None":
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _assigned_self_attrs(stmt: ast.stmt) -> "list[tuple[str, int]]":
    """self attributes a statement (re)binds or mutates in place."""
    out: list[tuple[str, int]] = []

    def targets_of(node: ast.AST) -> "list[ast.AST]":
        if isinstance(node, (ast.Tuple, ast.List)):
            return [t for e in node.elts for t in targets_of(e)]
        return [node]

    if isinstance(stmt, ast.Assign):
        tgts = [t for tgt in stmt.targets for t in targets_of(tgt)]
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        tgts = targets_of(stmt.target)
    else:
        tgts = []
    for t in tgts:
        attr = _self_attr(t)
        if attr is not None:
            out.append((attr, stmt.lineno))
        elif isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:  # self.x[k] = ... mutates x
                out.append((attr, stmt.lineno))
    return out


class _ClassInfo:
    def __init__(self, cls: ast.ClassDef) -> None:
        self.node = cls
        self.methods: dict[str, ast.FunctionDef] = {
            f.name: f for f in cls.body if isinstance(f, ast.FunctionDef)
        }
        self.export = next(
            (self.methods[n] for n in _EXPORT_NAMES if n in self.methods),
            None,
        )
        self.importer = next(
            (self.methods[n] for n in _IMPORT_NAMES if n in self.methods),
            None,
        )

    # --- field totality inputs ---

    def declared_fields(self) -> "dict[str, list[int]]":
        """attr -> declaring assignment lines (``__init__``/``attach_*``)."""
        decls: dict[str, list[int]] = {}
        for name, fn in self.methods.items():
            if name != "__init__" and not name.startswith("attach_"):
                continue
            for stmt in ast.walk(fn):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    for attr, lineno in _assigned_self_attrs(stmt):
                        decls.setdefault(attr, []).append(lineno)
        return decls

    def mutated_fields(self) -> "dict[str, tuple[int, str]]":
        """attr -> (line, method) of one mutation OUTSIDE ``__init__``."""
        mutated: dict[str, tuple[int, str]] = {}
        for name, fn in self.methods.items():
            if name == "__init__":
                continue
            for stmt in ast.walk(fn):
                if isinstance(stmt, ast.stmt):
                    for attr, lineno in _assigned_self_attrs(stmt):
                        mutated.setdefault(attr, (lineno, name))
                if (
                    isinstance(stmt, ast.Call)
                    and isinstance(stmt.func, ast.Attribute)
                    and stmt.func.attr in _MUTATOR_CALLS
                ):
                    attr = _self_attr(stmt.func.value)
                    if attr is not None:
                        mutated.setdefault(attr, (stmt.lineno, name))
        return mutated

    def export_reads(self) -> "set[str]":
        """self attributes the export method reads, one call level deep
        into same-class helpers (``self._helper(...)``)."""
        if self.export is None:
            return set()
        reads: set[str] = set()
        bodies = [self.export]
        for node in ast.walk(self.export):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _self_attr(node.func) is not None
                and node.func.attr in self.methods
            ):
                bodies.append(self.methods[node.func.attr])
        for body in bodies:
            for node in ast.walk(body):
                attr = _self_attr(node)
                if attr is not None and isinstance(node.ctx, ast.Load):
                    reads.add(attr)
        return reads

    # --- key symmetry inputs ---

    def _helper_calls(
        self, fn: ast.FunctionDef, dict_name: "str | None"
    ) -> "list[tuple[ast.FunctionDef, str | None]]":
        """Same-class helpers called from ``fn``; when ``dict_name`` is
        the state-dict variable and it is passed positionally, map it to
        the helper's matching parameter (the one-hop resolution)."""
        out: list[tuple[ast.FunctionDef, "str | None"]] = []
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _self_attr(node.func) is not None
                and node.func.attr in self.methods
            ):
                continue
            helper = self.methods[node.func.attr]
            params = [a.arg for a in helper.args.args if a.arg != "self"]
            mapped: "str | None" = None
            if dict_name is not None:
                for pos, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) and arg.id == dict_name:
                        if pos < len(params):
                            mapped = params[pos]
                        break
            out.append((helper, mapped))
        return out

    def export_keys(self) -> "dict[str, int]":
        """Snapshot keys the export writes: ``x["k"] = ...`` subscript
        stores plus top-level keys of dict literals returned or bound
        to a plain name (nested value dicts are the CHILD class's
        contract, not this one's)."""
        if self.export is None:
            return {}
        keys: dict[str, int] = {}
        bodies = [self.export] + [h for h, _ in self._helper_calls(self.export, None)]
        for body in bodies:
            for node in ast.walk(body):
                if (
                    isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Store)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                ):
                    keys.setdefault(node.slice.value, node.lineno)
                lit: "ast.Dict | None" = None
                if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
                    lit = node.value
                elif (
                    isinstance(node, (ast.Assign, ast.AnnAssign))
                    and isinstance(node.value, ast.Dict)
                ):
                    lit = node.value
                if lit is not None:
                    for k in lit.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            keys.setdefault(k.value, k.lineno)
        return keys

    def import_keys(self) -> "dict[str, int]":
        """Snapshot keys the import consumes off its state parameter:
        ``state["k"]`` loads, ``state.get("k", ...)``, ``"k" in state``
        — one call level deep when the dict is handed to a helper."""
        if self.importer is None:
            return {}
        params = [a.arg for a in self.importer.args.args if a.arg != "self"]
        if not params:
            return {}
        keys: dict[str, int] = {}
        scopes: list[tuple[ast.FunctionDef, str]] = [(self.importer, params[0])]
        scopes += [
            (h, p)
            for h, p in self._helper_calls(self.importer, params[0])
            if p is not None
        ]
        for body, state_name in scopes:
            for node in ast.walk(body):
                if (
                    isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == state_name
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                ):
                    keys.setdefault(node.slice.value, node.lineno)
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == state_name
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    keys.setdefault(node.args[0].value, node.lineno)
                elif isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
                ):
                    if (
                        isinstance(node.left, ast.Constant)
                        and isinstance(node.left.value, str)
                        and any(
                            isinstance(c, ast.Name) and c.id == state_name
                            for c in node.comparators
                        )
                    ):
                        keys.setdefault(node.left.value, node.lineno)
        return keys


def check_state(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    violations: list[Violation] = []
    for relpath, class_names in ROSTER:
        path = root / relpath
        if not path.exists():
            continue
        try:
            src = core.source(path)
            tree = core.parse(path)
        except SyntaxError:
            continue
        lines = src.splitlines()
        classes = {
            n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
        }
        for cls_name in class_names:
            if cls_name not in classes:
                continue
            info = _ClassInfo(classes[cls_name])
            export_name = (
                info.export.name if info.export is not None else None
            )
            declared = info.declared_fields()
            mutated = info.mutated_fields()
            reads = info.export_reads()

            # 1. field totality
            for attr in sorted(set(declared) & set(mutated)):
                if attr in reads:
                    continue
                decl_lines = declared[attr]
                reason = next(
                    (
                        r
                        for ln in decl_lines
                        if (r := _ephemeral_reason(lines, ln)) is not False
                    ),
                    False,
                )
                mut_line, mut_method = mutated[attr]
                if reason is False:
                    where = (
                        f"read by {export_name}" if export_name
                        else "covered by any export method"
                    )
                    violations.append(
                        Violation(
                            "state", relpath, decl_lines[0],
                            f"{cls_name}.{attr}: mutable runtime state "
                            f"(mutated at line {mut_line} in {mut_method}) "
                            f"is not {where} — checkpoint resume silently "
                            "loses it; export it or annotate "
                            "'# ephemeral: <reason>'",
                            f"state:{relpath}::{cls_name}.{attr}",
                        )
                    )
                elif reason == "":
                    violations.append(
                        Violation(
                            "state", relpath, decl_lines[0],
                            f"{cls_name}.{attr}: '# ephemeral:' annotation "
                            "requires a reason",
                            f"state:{relpath}::{cls_name}.{attr}::reason",
                        )
                    )

            # 2. export/import key symmetry
            if info.export is None or info.importer is None:
                continue
            ex_keys = info.export_keys()
            im_keys = info.import_keys()
            for key in sorted(set(ex_keys) - set(im_keys)):
                violations.append(
                    Violation(
                        "state", relpath, ex_keys[key],
                        f"{cls_name}: snapshot key {key!r} is written by "
                        f"{info.export.name} but never consumed by "
                        f"{info.importer.name} — resume silently drops it",
                        f"state:{relpath}::{cls_name}[{key}]:export-only",
                    )
                )
            for key in sorted(set(im_keys) - set(ex_keys)):
                violations.append(
                    Violation(
                        "state", relpath, im_keys[key],
                        f"{cls_name}: snapshot key {key!r} is consumed by "
                        f"{info.importer.name} but never written by "
                        f"{info.export.name} — it can never arrive",
                        f"state:{relpath}::{cls_name}[{key}]:import-only",
                    )
                )
    uniq: dict[str, Violation] = {}
    for v in violations:
        uniq.setdefault(v.key, v)
    return list(uniq.values())
