"""Timing/logging-path lint: spans and metrics are the only sanctioned
timing path.

Two invariants over ``tpfl/``, ``tools/`` and the root bench/dryrun
scripts (the management layer is exempt — it IS the telemetry/
profiling implementation and owns the wall-clock anchor; ``tools/perf``
is exempt — superseded lab-notebook scratch scripts, see their
README):

1. **No ``time.time()``** — every duration, deadline, and stamp must
   come from ``time.monotonic()`` / ``time.perf_counter()`` (NTP-step
   immunity — the aggregator stall clock and round deadlines moved
   first; this lint keeps the rest, INCLUDING new timing code in the
   bench and the profiling subsystem's call sites, from regressing) or
   flow through the spans in :mod:`tpfl.management.tracing` /
   :mod:`tpfl.management.profiling`, which timestamp monotonically and
   carry the process wall anchor for cross-process merges.

2. **No raw ``logging`` calls** — ``logging.getLogger``/``logging.info``
   etc. bypass the framework logger's routing (node tagging, async
   queue, web push) and the metrics registry. Everything observable
   goes through ``tpfl.management.logger`` / ``logger.metrics``.

AST-based (docstrings and comments mentioning ``time.time()`` don't
count — only actual call sites).
"""

from __future__ import annotations

import ast
import pathlib

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

#: Modules exempt from the lint: the management layer implements the
#: telemetry/logging machinery itself (the flight recorder's wall
#: anchor is the one sanctioned ``time.time()`` call). NEW management
#: modules are NOT automatically exempt — they consume the telemetry
#: core like everyone else; the ledger (PR 7) is the first one linted.
ALLOWED_PREFIX = "tpfl/management/"

#: Management modules the lint DOES cover (consumers of the telemetry
#: core, not implementors of it).
LINTED_MANAGEMENT = (
    "tpfl/management/ledger.py",
    "tpfl/management/quarantine.py",
    "tpfl/management/engine_obs.py",
)

_LOGGING_CALLS = {
    "debug", "info", "warning", "error", "critical", "exception",
    "log", "getLogger", "basicConfig",
}


#: Lab-notebook scratch scripts (tools/perf/README.md): frozen
#: measurement receipts, not maintained code — outside the lint.
EXEMPT_PREFIXES = ("tools/perf/",)

#: Root-level scripts with timing code the lint also covers (new
#: timing in the bench must ride monotonic()/perf_counter() or the
#: profiling API, same as the package).
ROOT_SCRIPTS = ("bench.py", "__graft_entry__.py")


def _lint_files(root: "pathlib.Path") -> "list[pathlib.Path]":
    files = list(py_files(root))
    files += py_files(root, "tools")
    files += [root / s for s in ROOT_SCRIPTS if (root / s).exists()]
    return files


def check_trace(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    out: list[Violation] = []
    for path in _lint_files(root):
        r = rel(root, path)
        if r.startswith(ALLOWED_PREFIX) and r not in LINTED_MANAGEMENT:
            continue
        if any(r.startswith(p) for p in EXEMPT_PREFIXES):
            continue
        tree = core.parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
            ):
                continue
            if fn.value.id == "time" and fn.attr == "time":
                out.append(
                    Violation(
                        "trace", r, node.lineno,
                        "time.time() outside tpfl/management — use "
                        "time.monotonic() (NTP-step immune) or a tracing "
                        "span (tpfl.management.tracing)",
                        f"trace:{r}:{node.lineno}",
                    )
                )
            elif fn.value.id == "logging" and fn.attr in _LOGGING_CALLS:
                out.append(
                    Violation(
                        "trace", r, node.lineno,
                        f"raw logging.{fn.attr}() outside tpfl/management — "
                        "route through tpfl.management.logger (node "
                        "tagging, async queue, metrics registry)",
                        f"trace:{r}:{node.lineno}",
                    )
                )
    return out
