"""Timing/logging-path lint: spans and metrics are the only sanctioned
timing path.

Two invariants over ``tpfl/`` (the management layer is exempt — it IS
the telemetry implementation and owns the wall-clock anchor):

1. **No ``time.time()``** — every duration, deadline, and stamp in the
   protocol must come from ``time.monotonic()`` (NTP-step immunity —
   the aggregator stall clock and round deadlines moved first; this
   lint keeps the rest from regressing) or flow through the tracing
   spans in :mod:`tpfl.management.tracing`, which timestamp
   monotonically and carry the process wall anchor for cross-process
   merges.

2. **No raw ``logging`` calls** — ``logging.getLogger``/``logging.info``
   etc. bypass the framework logger's routing (node tagging, async
   queue, web push) and the metrics registry. Everything observable
   goes through ``tpfl.management.logger`` / ``logger.metrics``.

AST-based (docstrings and comments mentioning ``time.time()`` don't
count — only actual call sites).
"""

from __future__ import annotations

import ast
import pathlib

from tools.tpflcheck.core import Violation, py_files, rel, repo_root

#: Modules exempt from the lint: the management layer implements the
#: telemetry/logging machinery itself (the flight recorder's wall
#: anchor is the one sanctioned ``time.time()`` call).
ALLOWED_PREFIX = "tpfl/management/"

_LOGGING_CALLS = {
    "debug", "info", "warning", "error", "critical", "exception",
    "log", "getLogger", "basicConfig",
}


def check_trace(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    out: list[Violation] = []
    for path in py_files(root):
        r = rel(root, path)
        if r.startswith(ALLOWED_PREFIX):
            continue
        tree = ast.parse(path.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
            ):
                continue
            if fn.value.id == "time" and fn.attr == "time":
                out.append(
                    Violation(
                        "trace", r, node.lineno,
                        "time.time() outside tpfl/management — use "
                        "time.monotonic() (NTP-step immune) or a tracing "
                        "span (tpfl.management.tracing)",
                        f"trace:{r}:{node.lineno}",
                    )
                )
            elif fn.value.id == "logging" and fn.attr in _LOGGING_CALLS:
                out.append(
                    Violation(
                        "trace", r, node.lineno,
                        f"raw logging.{fn.attr}() outside tpfl/management — "
                        "route through tpfl.management.logger (node "
                        "tagging, async queue, metrics registry)",
                        f"trace:{r}:{node.lineno}",
                    )
                )
    return out
