"""Wire-path lints.

Three checks sharing tpflcheck's walk and reporting machinery
(``python -m tools.tpflcheck`` runs everything):

- :func:`check` — model payloads must go through the codec registry:
  raw ``serialization.encode_pytree`` / ``encode_model_payload`` /
  ``msgpack.packb`` outside the allowlisted modules bypasses the
  versioned codec envelope (``tpfl/learning/compression.py``) — such
  payloads never quantize, never delta-encode, and old/new peers can
  silently stop agreeing on the wire format.
- :func:`check_copies` — array bytes must not be copied outside the
  serialization layer: a stray ``.tobytes()`` or
  ``frombuffer(...).copy()`` reintroduces exactly the per-leaf memcpy
  the v3 zero-copy layout removed, silently (payloads still
  round-trip).
- :func:`check_rpc` — no code outside the transport layer may invoke a
  gRPC stub/channel or call ``_transport_send`` directly; every
  outbound message must flow through
  ``ThreadedCommunicationProtocol.send``, where retry/backoff, the
  circuit breaker, the fault injector, and the send-health counters
  live.

Each returns ``['path:line: offending text', ...]`` (the legacy
interface the test suite asserts on); :func:`violations` adapts all
three to tpflcheck's :class:`~tools.tpflcheck.core.Violation` stream.
"""

from __future__ import annotations

import pathlib
import re

from tools.tpflcheck.core import Violation, py_files, rel, repo_root

ALLOWED = {
    # the v1 envelope implementation
    "tpfl/learning/serialization.py",
    # the v2 codec implementation
    "tpfl/learning/compression.py",
    # encode_parameters — the registry dispatch itself (dense-vs-codec)
    "tpfl/learning/model.py",
    # transport framing (control fields + already-encoded payload bytes)
    "tpfl/communication/message.py",
    # RPC control frames and chunk frames around already-encoded bytes
    "tpfl/communication/grpc_transport.py",
    # on-DISK format, deliberately exact (never rides the wire)
    "tpfl/management/checkpoint.py",
}

# Raw serialization entry points a wire path must not touch directly.
PATTERN = re.compile(
    r"(?<![\w.])(?:serialization\.)?(?:encode_pytree|encode_model_payload)\s*\("
    r"|msgpack\.packb\s*\("
)


def check(repo: "pathlib.Path | None" = None) -> list[str]:
    """Return a list of 'path:line: offending text' violations."""
    root = repo_root(repo)
    out: list[str] = []
    for path in py_files(root):
        r = rel(root, path)
        if r in ALLOWED:
            continue
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            stripped = line.split("#", 1)[0]
            m = PATTERN.search(stripped)
            if m is None:
                continue
            # compression.encode_model_payload IS the registry path.
            if "compression.encode_model_payload" in stripped:
                continue
            out.append(f"{r}:{lineno}: {line.strip()}")
    return out


# The zero-copy model plane routes every leaf-byte extraction through
# serialization.leaf_bytes (borrowed memoryview, no copy) and every
# decode through zero-copy frombuffer views.
COPIES_ALLOWED = {
    "tpfl/learning/serialization.py",
    "tpfl/learning/compression.py",
}

COPY_PATTERN = re.compile(
    r"\.tobytes\s*\(" r"|frombuffer\s*\([^)]*\)\s*\.copy\s*\("
)


def check_copies(repo: "pathlib.Path | None" = None) -> list[str]:
    """Return 'path:line: offending text' for array-byte copies outside
    the serialization layer (route through serialization.leaf_bytes /
    the versioned decode views)."""
    root = repo_root(repo)
    out: list[str] = []
    for path in py_files(root):
        r = rel(root, path)
        if r in COPIES_ALLOWED:
            continue
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            stripped = line.split("#", 1)[0]
            if COPY_PATTERN.search(stripped):
                out.append(f"{r}:{lineno}: {line.strip()}")
    return out


# The only module allowed to touch gRPC stubs/channels.
RPC_ALLOWED = {
    "tpfl/communication/grpc_transport.py",
}

# The only modules allowed to call the raw transport hook: base.py owns
# the retrying dispatch (and the disconnect farewell, deliberately
# fire-once); the transports implement the hook.
SEND_ALLOWED = {
    "tpfl/communication/base.py",
    "tpfl/communication/grpc_transport.py",
    "tpfl/communication/memory.py",
}

# Raw RPC entry points: stub tables, channel construction, stub calls.
RPC_PATTERN = re.compile(
    r"""\[['"]stubs['"]\]"""
    r"|\.unary_unary\s*\("
    r"|\.unary_stream\s*\("
    r"|\.stream_unary\s*\("
    r"|grpc\.(?:insecure|secure)_channel\s*\("
)

# Direct transport-hook calls (not the `def` lines that implement it).
SEND_PATTERN = re.compile(r"\._transport_send(?:_corrupted)?\s*\(")


def check_rpc(repo: "pathlib.Path | None" = None) -> list[str]:
    """Return 'path:line: offending text' for outbound RPC call sites
    that bypass the retrying send path."""
    root = repo_root(repo)
    out: list[str] = []
    for path in py_files(root):
        r = rel(root, path)
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            stripped = line.split("#", 1)[0]
            if r not in RPC_ALLOWED and RPC_PATTERN.search(stripped):
                out.append(f"{r}:{lineno}: {line.strip()}")
            elif r not in SEND_ALLOWED and SEND_PATTERN.search(stripped):
                out.append(f"{r}:{lineno}: {line.strip()}")
    return out


def violations(repo: "pathlib.Path | None" = None) -> list[Violation]:
    """All three wire checks as tpflcheck Violations."""
    out: list[Violation] = []
    for name, fn, hint in (
        ("wire", check, "serialize through the codec registry"),
        ("wire-copies", check_copies, "route through serialization.leaf_bytes"),
        ("wire-rpc", check_rpc, "route through ThreadedCommunicationProtocol.send"),
    ):
        for entry in fn(repo):
            loc, _, text = entry.partition(": ")
            file, _, line = loc.rpartition(":")
            out.append(
                Violation(
                    check=name,
                    file=file,
                    line=int(line or 0),
                    message=f"{text} ({hint})",
                    key=f"{name}:{loc}",
                )
            )
    return out
