"""Shared machinery for the tpflcheck static-analysis suite.

Every check produces :class:`Violation` records over the same file walk
(:func:`py_files`), and waivers live as reviewable DATA in
``pyproject.toml`` (``[tool.tpflcheck] waivers``) rather than code
edits — a waiver is ``"<key> = <reason>"`` and a reason is mandatory:
the suite fails on waivers without one ("zero unexplained waivers"),
and warns about waivers that no longer match anything so the list
cannot rot.

Waiver keys are what each check reports in its violation output, e.g.::

    guards:tpfl/learning/aggregators/aggregator.py::Aggregator._covered_meets_quorum::_train_set

A waiver may also end with ``::*`` to waive every attribute in a
function (``guards:<file>::<qualname>::*``) — used for helpers whose
docstring already states "caller holds the lock".
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional


@dataclass
class Violation:
    check: str  # "guards" | "locks" | "layers" | "knobs" | "threads" | "wire" ...
    file: str  # repo-relative posix path ("" for repo-wide findings)
    line: int
    message: str
    key: str  # what a waiver must match

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.file else "(repo)"
        return f"[{self.check}] {loc}: {self.message}"


def repo_root(explicit: "pathlib.Path | None" = None) -> pathlib.Path:
    if explicit is not None:
        return pathlib.Path(explicit)
    return pathlib.Path(__file__).resolve().parent.parent.parent


def py_files(
    root: pathlib.Path, subdir: str = "tpfl"
) -> list[pathlib.Path]:
    return sorted(
        p
        for p in (root / subdir).rglob("*.py")
        if "__pycache__" not in p.parts
    )


def rel(root: pathlib.Path, path: pathlib.Path) -> str:
    return path.relative_to(root).as_posix()


# --- shared source / AST cache --------------------------------------------
#
# Fourteen passes walk the same ~hundred files; parsing dominates the
# suite's wall time, and re-parsing per pass multiplies it fourteen-
# fold. Both caches key on (path, mtime_ns, size) so a rewritten file
# (the fixture-repo tests edit files in place) re-parses, while the
# unchanged tree is shared across every pass in the process. Passes
# must treat cached trees as READ-ONLY — none attaches attributes to
# AST nodes today; keep it that way.

_SRC_CACHE: "dict[tuple[str, int, int], str]" = {}
_AST_CACHE: "dict[tuple[str, int, int], ast.Module]" = {}


def _cache_key(path: pathlib.Path) -> "tuple[str, int, int]":
    st = path.stat()
    return (str(path), st.st_mtime_ns, st.st_size)


def source(path: pathlib.Path) -> str:
    """``path.read_text()`` through the shared per-process cache."""
    key = _cache_key(path)
    src = _SRC_CACHE.get(key)
    if src is None:
        src = path.read_text(encoding="utf-8")
        _SRC_CACHE[key] = src
    return src


def parse(path: pathlib.Path) -> ast.Module:
    """``ast.parse`` of ``path`` through the shared per-process cache.
    Raises ``SyntaxError`` like ``ast.parse`` — callers that tolerate
    unparsable files keep their own try/except."""
    key = _cache_key(path)
    tree = _AST_CACHE.get(key)
    if tree is None:
        tree = ast.parse(source(path))
        _AST_CACHE[key] = tree
    return tree


# --- waivers --------------------------------------------------------------

_SECTION_RE = re.compile(r"^\[tool\.tpflcheck\]\s*$")
_ANY_SECTION_RE = re.compile(r"^\[[^\]]+\]\s*$")
_STRING_RE = re.compile(r'"((?:[^"\\]|\\.)*)"')


@dataclass
class Waivers:
    """key -> reason, plus bookkeeping for unused/unexplained checks."""

    reasons: dict[str, str] = field(default_factory=dict)
    unexplained: list[str] = field(default_factory=list)  # entries w/o reason
    _used: set[str] = field(default_factory=set)

    def match(self, key: str) -> Optional[str]:
        """Reason when ``key`` is waived (exact, or function-wide via a
        ``::*`` suffix entry), else None. Marks the waiver used."""
        reason = self.reasons.get(key)
        if reason is not None:
            self._used.add(key)
            return reason
        # guards:<file>::<qualname>::<attr> -> try guards:<file>::<qualname>::*
        if "::" in key:
            wide = key.rsplit("::", 1)[0] + "::*"
            reason = self.reasons.get(wide)
            if reason is not None:
                self._used.add(wide)
                return reason
        return None

    def unused(self) -> list[str]:
        return sorted(set(self.reasons) - self._used)


def load_waivers(root: pathlib.Path) -> Waivers:
    """Parse ``[tool.tpflcheck] waivers`` from pyproject.toml.

    Python 3.10 has no ``tomllib``; the section only needs an array of
    strings, so a line parser suffices (and keeps the checker
    dependency-free). Each entry is ``"<key> = <reason>"``."""
    w = Waivers()
    pyproject = root / "pyproject.toml"
    if not pyproject.exists():
        return w
    in_section = in_array = False
    for raw in pyproject.read_text(encoding="utf-8").splitlines():
        line = raw.strip()
        if _SECTION_RE.match(line):
            in_section = True
            continue
        if in_section and _ANY_SECTION_RE.match(line):
            break  # next section
        if not in_section:
            continue
        if line.startswith("waivers"):
            in_array = "[" in line and "]" not in line.split("#", 1)[0]
            for entry in _STRING_RE.findall(line):
                _add_waiver(w, entry)
            continue
        if in_array:
            for entry in _STRING_RE.findall(line):
                _add_waiver(w, entry)
            if "]" in line.split("#", 1)[0]:
                in_array = False
    return w


def _add_waiver(w: Waivers, entry: str) -> None:
    key, sep, reason = entry.partition(" = ")
    key, reason = key.strip(), reason.strip()
    if not sep or not reason:
        w.unexplained.append(entry)
        return
    w.reasons[key] = reason


def apply_waivers(
    violations: Iterable[Violation], waivers: Waivers
) -> tuple[list[Violation], list[str]]:
    """Split into (kept, waived-descriptions)."""
    kept: list[Violation] = []
    waived: list[str] = []
    for v in violations:
        reason = waivers.match(v.key)
        if reason is None:
            kept.append(v)
        else:
            waived.append(f"{v.key}  (waived: {reason})")
    return kept, waived
