"""Thread-lifecycle lint.

Every thread in ``tpfl/`` must be identifiable in a deadlock witness
chain, a lock trace, or a py-spy dump — ``Thread-7`` is not a
diagnosis. Three rules:

- ``threading.Thread(...)`` call sites pass BOTH ``name=`` and
  ``daemon=`` explicitly (daemon-ness is a shutdown-semantics decision
  that should be visible at the creation site, not inherited);
- classes subclassing ``threading.Thread`` pass ``name=`` and
  ``daemon=`` through their ``super().__init__`` call;
- ``ThreadPoolExecutor(...)`` passes ``thread_name_prefix=``.
"""

from __future__ import annotations

import ast

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _kwargs(call: ast.Call) -> set[str]:
    return {k.arg for k in call.keywords if k.arg is not None}


def _subclasses_thread(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = (
            base.id
            if isinstance(base, ast.Name)
            else base.attr if isinstance(base, ast.Attribute) else ""
        )
        if name == "Thread":
            return True
    return False


def check_threads(repo=None) -> list[Violation]:
    root = repo_root(repo)
    violations: list[Violation] = []
    for path in py_files(root):
        r = rel(root, path)
        tree = core.parse(path)

        # Which Call nodes are super().__init__ inside Thread subclasses
        # (those are checked by the subclass rule, not the call rule).
        thread_subclass_inits: set[ast.Call] = set()
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            if not _subclasses_thread(cls):
                continue
            init = next(
                (
                    f
                    for f in cls.body
                    if isinstance(f, ast.FunctionDef) and f.name == "__init__"
                ),
                None,
            )
            if init is None:
                violations.append(
                    Violation(
                        "threads", r, cls.lineno,
                        f"{cls.name} subclasses Thread without an "
                        "__init__ setting name=/daemon=",
                        f"threads:{r}::{cls.name}",
                    )
                )
                continue
            super_init = None
            for node in ast.walk(init):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "__init__"
                ):
                    super_init = node
                    thread_subclass_inits.add(node)
            if super_init is None:
                violations.append(
                    Violation(
                        "threads", r, init.lineno,
                        f"{cls.name}.__init__ never calls "
                        "super().__init__ (thread gets a default name)",
                        f"threads:{r}::{cls.name}",
                    )
                )
            else:
                missing = {"name", "daemon"} - _kwargs(super_init)
                if missing:
                    violations.append(
                        Violation(
                            "threads", r, super_init.lineno,
                            f"{cls.name}'s super().__init__ is missing "
                            f"{sorted(missing)} — traced-lock/deadlock "
                            "reports would show 'Thread-N'",
                            f"threads:{r}::{cls.name}",
                        )
                    )

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or node in thread_subclass_inits:
                continue
            name = _call_name(node)
            if name == "Thread":
                missing = {"name", "daemon"} - _kwargs(node)
                if missing:
                    violations.append(
                        Violation(
                            "threads", r, node.lineno,
                            f"threading.Thread(...) without explicit "
                            f"{sorted(missing)}",
                            f"threads:{r}:{node.lineno}",
                        )
                    )
            elif name == "ThreadPoolExecutor":
                if "thread_name_prefix" not in _kwargs(node):
                    violations.append(
                        Violation(
                            "threads", r, node.lineno,
                            "ThreadPoolExecutor(...) without "
                            "thread_name_prefix=",
                            f"threads:{r}:{node.lineno}",
                        )
                    )
    return violations
