"""SPMD collective/axis lint.

Two JAX-semantics invariants over ``tpfl/``:

1. **Axis binding** — every named-axis collective
   (``lax.psum`` / ``pmean`` / ``psum_scatter`` / ``all_gather`` /
   ``all_to_all`` / ``ppermute`` / ``axis_index`` / …) must name an
   axis that is BOUND by an enclosing ``shard_map`` / ``vmap`` /
   ``pmap`` in the same statically-visible scope. An unbound axis name
   is an eager ``NameError`` only on the paths a test actually runs —
   on the untested variant it is a latent crash. Resolution:

   - string literals and module-level string constants resolve
     directly (one import hop: ``NODE_AXIS`` / ``MODEL_AXIS`` /
     ``FSDP_AXIS`` / ``TP_AXIS`` from ``tpfl.parallel.mesh`` — the 2D
     ``nodes x model`` mesh's axis names ride the same rule);
   - an axis that is a function PARAMETER is fine locally ("runs
     inside the caller's shard_map" — the inner-fn contract); the
     obligation transfers to statically-resolvable call sites
     (one-level resolution like ``locks.py``: bare same-module calls,
     ``self.`` methods, and ``partial(fn, axis_name=...)``), walked up
     until a scope either binds the axis or passes its own parameter
     outward (a public inner API — callers outside the repo bind it);
   - a scope "binds" an axis when the axis name (or the constant that
     resolves to it) appears in a ``PartitionSpec(...)``, an
     ``axis_name=`` / ``axis_names=`` keyword, or a mesh axis dict in
     the same outermost function (or at module level).

2. **Dead axis_index** — a ``lax.axis_index(...)`` whose result is
   never consumed is an error, not dead weight: XLA's sharding
   propagation flows from USERS, so a user-less ``axis_index`` inside
   a custom-call jaxpr never receives the ``{manual}`` sharding and
   the SPMD partitioner rejects the whole program — the exact
   dead-``axis_index`` lowering that broke the flash ring's
   partitioning (fixed in PR 10). The result must be assigned to a
   name that is later read (anywhere in the enclosing function,
   nested closures included) or used directly in an expression.

Waiver keys: ``spmd:<file>:<line>`` (unbound axis) and
``spmd:<file>:<line>:dead`` (dead axis_index).
"""

from __future__ import annotations

import ast
import pathlib

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

#: collective -> positional index of its axis-name argument.
COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "psum_scatter": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "ppermute": 1,
    "pshuffle": 1,
    "axis_index": 0,
    "axis_size": 0,
}

_BINDING_CALLS = ("shard_map", "vmap", "pmap", "xmap")


def _collective_name(call: ast.Call) -> "str | None":
    """'psum' for ``lax.psum`` / ``jax.lax.psum`` / bare ``psum``."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in COLLECTIVES:
        return fn.id
    if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVES:
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "lax":
            return fn.attr
        if (
            isinstance(base, ast.Attribute)
            and base.attr == "lax"
            and isinstance(base.value, ast.Name)
            and base.value.id == "jax"
        ):
            return fn.attr
    return None


def _axis_expr(call: ast.Call, name: str) -> "ast.expr | None":
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis"):
            return kw.value
    idx = COLLECTIVES[name]
    if idx < len(call.args):
        return call.args[idx]
    return None


class _ModuleConstants:
    """Module-level string constants, with one import hop into tpfl."""

    def __init__(self, root: pathlib.Path) -> None:
        self.root = root
        self._consts: dict[str, dict[str, str]] = {}  # relpath -> name -> s

    def constants(self, relpath: str) -> dict[str, str]:
        cached = self._consts.get(relpath)
        if cached is not None:
            return cached
        out: dict[str, str] = {}
        path = self.root / relpath
        if path.exists():
            try:
                tree = core.parse(path)
            except SyntaxError:
                tree = ast.Module(body=[], type_ignores=[])
            for node in tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                    if (
                        isinstance(tgt, ast.Name)
                        and isinstance(val, ast.Constant)
                        and isinstance(val.value, str)
                    ):
                        out[tgt.id] = val.value
        self._consts[relpath] = out
        return out


def _import_map(tree: ast.Module) -> dict[str, str]:
    """imported name -> tpfl module relpath (for constant resolution)."""
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module:
            if not node.module.startswith("tpfl"):
                continue
            relpath = node.module.replace(".", "/") + ".py"
            for a in node.names:
                out[a.asname or a.name] = relpath
    return out


class _Scope:
    """One function def with its parent chain and local assignments."""

    def __init__(self, fn: ast.AST, parent: "._Scope | None", cls: "str | None"):
        self.fn = fn
        self.parent = parent
        self.cls = cls
        args = fn.args
        self.params = [
            a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)
        ]
        self.defaults: dict[str, ast.expr] = {}
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            self.defaults[a.arg] = d
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                self.defaults[a.arg] = d
        self.assigns: dict[str, ast.expr] = {}

        def visit(node: ast.AST, top: bool = False) -> None:
            if not top and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self.assigns[t.id] = node.value
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(fn, top=True)

    def outermost(self) -> "._Scope":
        s = self
        while s.parent is not None:
            s = s.parent
        return s

    def lookup(self, name: str) -> "tuple[str, ast.expr | None]":
        """('param', None) | ('local', expr) | ('unknown', None),
        walking the closure chain."""
        s: "_Scope | None" = self
        while s is not None:
            if name in s.assigns:
                return ("local", s.assigns[name])
            if name in s.params:
                return ("param", None)
            s = s.parent
        return ("unknown", None)


class _ModuleInfo:
    """Per-file scopes, binding sets, collective sites, call edges."""

    def __init__(self, relpath: str, tree: ast.Module, consts: _ModuleConstants):
        self.relpath = relpath
        self.tree = tree
        self.local_consts = {
            t.id: v.value
            for t, v in (
                (n.targets[0], n.value)
                for n in tree.body
                if isinstance(n, ast.Assign) and len(n.targets) == 1
            )
            if isinstance(t, ast.Name)
            and isinstance(v, ast.Constant)
            and isinstance(v.value, str)
        }
        self.imports = _import_map(tree)
        self._consts = consts
        self.scopes: dict[int, _Scope] = {}  # id(fn node) -> scope
        self.fn_by_name: dict[tuple["str | None", str], ast.AST] = {}
        self.module_bindings: set[str] = set()
        self._index(tree)

    def _index(self, tree: ast.Module) -> None:
        def walk(node: ast.AST, parent: "._Scope | None", cls: "str | None"):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, parent, child.name)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    scope = _Scope(child, parent, cls)
                    self.scopes[id(child)] = scope
                    self.fn_by_name.setdefault((cls, child.name), child)
                    if cls is not None:
                        # bare-name resolution also finds methods
                        self.fn_by_name.setdefault((None, child.name), child)
                    walk(child, scope, cls)
                else:
                    walk(child, parent, cls)

        walk(tree, None, None)
        self.module_bindings = self._bindings(tree)

    def _bindings(self, node: ast.AST) -> set[str]:
        """Axis symbols bound in ``node``'s subtree: names/strings in
        PartitionSpec(...), axis_name(s)= kwargs, mesh axis dicts."""
        out: set[str] = set()

        def add_expr(e: ast.AST) -> None:
            for sub in ast.walk(e):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
                elif isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    out.add(sub.value)

        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            fname = (
                f.id
                if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else ""
            )
            if fname == "PartitionSpec":
                for a in sub.args:
                    add_expr(a)
            if fname in ("create_mesh", "Mesh", "make_mesh"):
                for a in list(sub.args) + [k.value for k in sub.keywords]:
                    if isinstance(a, ast.Dict):
                        for k in a.keys:
                            if k is not None:
                                add_expr(k)
                    else:
                        add_expr(a)
            for kw in sub.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    add_expr(kw.value)
        return out

    def outer_bindings(self, scope: _Scope) -> set[str]:
        return self._bindings(scope.outermost().fn) | self.module_bindings

    def resolve_to_strings(self, name: str) -> set[str]:
        """Constant strings a bare name may denote (local module
        constant or a one-hop tpfl import)."""
        out: set[str] = set()
        if name in self.local_consts:
            out.add(self.local_consts[name])
        src = self.imports.get(name)
        if src is not None:
            v = self._consts.constants(src).get(name)
            if v is not None:
                out.add(v)
        return out


def _axis_symbols(
    expr: ast.AST, scope: _Scope, mod: _ModuleInfo, depth: int = 0
) -> "tuple[set[str], bool]":
    """(symbols, param_rooted): names/strings the axis expression may
    denote, and whether any path roots in a function parameter."""
    if depth > 4:
        return set(), False
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, str):
            return {expr.value}, False
        return set(), False
    if isinstance(expr, ast.IfExp):
        s1, p1 = _axis_symbols(expr.body, scope, mod, depth + 1)
        s2, p2 = _axis_symbols(expr.orelse, scope, mod, depth + 1)
        return s1 | s2, p1 or p2
    if isinstance(expr, ast.Name):
        kind, bound = scope.lookup(expr.id)
        if kind == "param":
            return {expr.id}, True
        if kind == "local" and bound is not None:
            syms, rooted = _axis_symbols(bound, scope, mod, depth + 1)
            return syms | {expr.id}, rooted
        # module constant / import
        strings = mod.resolve_to_strings(expr.id)
        if strings:
            return strings | {expr.id}, False
        return {expr.id}, False
    return set(), False


def _own_nodes(fn: ast.AST):
    """Nodes belonging to ``fn``'s own scope: the walk stops at nested
    FunctionDefs (their own _Scope covers them) but descends into
    lambdas (which share the enclosing scope here)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _call_edges(
    mod: _ModuleInfo,
) -> "dict[tuple[str | None, str], list[tuple[_Scope, ast.Call, str | None]]]":
    """callee (cls, name) -> [(caller scope, call node, partial kw)]
    for bare-name, self.-method, and partial(fn, ...) call sites."""
    edges: dict = {}
    for fn_id, scope in mod.scopes.items():
        for node in _own_nodes(scope.fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            target: "tuple[str | None, str] | None" = None
            call = node
            if isinstance(f, ast.Name):
                if f.id == "partial" and node.args:
                    inner = node.args[0]
                    if isinstance(inner, ast.Name):
                        target = (None, inner.id)
                else:
                    target = (None, f.id)
            elif isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ) and f.value.id in ("self", "cls"):
                target = (scope.cls, f.attr)
            if target is None:
                continue
            edges.setdefault(target, []).append((scope, call, None))
    return edges


def _arg_for_param(
    call: ast.Call, callee_scope: _Scope, param: str
) -> "ast.expr | None":
    """The expression the call passes for ``param`` (positional,
    keyword, or the callee's default)."""
    for kw in call.keywords:
        if kw.arg == param:
            return kw.value
    params = [p for p in callee_scope.params if p not in ("self", "cls")]
    # partial(fn, ...) positional offset: first arg is the fn itself
    args = list(call.args)
    if (
        isinstance(call.func, ast.Name)
        and call.func.id == "partial"
        and args
    ):
        args = args[1:]
    try:
        idx = params.index(param)
    except ValueError:
        return None
    if idx < len(args):
        return args[idx]
    return callee_scope.defaults.get(param)


def check_spmd(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    consts = _ModuleConstants(root)
    violations: list[Violation] = []
    for path in py_files(root):
        r = rel(root, path)
        src = core.source(path)
        # Cheap textual pre-filter: most modules have no collectives
        # at all — skip the full scope/edge index for them.
        if not any(
            tok in src
            for tok in ("psum", "all_gather", "axis_index", "ppermute",
                        "pmean", "pmax", "pmin", "all_to_all", "pshuffle")
        ):
            continue
        try:
            tree = core.parse(path)
        except SyntaxError:
            continue
        mod = _ModuleInfo(r, tree, consts)
        edges = _call_edges(mod)
        for fn_id, scope in mod.scopes.items():
            for node in _own_nodes(scope.fn):
                if not isinstance(node, ast.Call):
                    continue
                cname = _collective_name(node)
                if cname is None:
                    continue
                axis = _axis_expr(node, cname)
                if axis is None:
                    continue
                if not _axis_bound(mod, edges, scope, axis, set()):
                    violations.append(
                        Violation(
                            "spmd", r, node.lineno,
                            f"lax.{cname} names an axis that no enclosing "
                            "shard_map/vmap/pmap binds in any statically-"
                            "visible caller — an unbound axis name fails "
                            "only on the (untested) path that traces it",
                            f"spmd:{r}:{node.lineno}",
                        )
                    )
                if cname == "axis_index":
                    v = _dead_axis_index(mod, scope, node)
                    if v is not None:
                        violations.append(
                            Violation(
                                "spmd", r, v,
                                "axis_index result is never consumed — a "
                                "user-less axis_index never receives the "
                                "{manual} sharding and the SPMD "
                                "partitioner rejects the program (the "
                                "PR-10 flash-ring bug class); delete it "
                                "or consume its result",
                                f"spmd:{r}:{v}:dead",
                            )
                        )
        # dedupe
    uniq: dict[str, Violation] = {}
    for v in violations:
        uniq.setdefault(v.key, v)
    return list(uniq.values())


def _axis_bound(
    mod: _ModuleInfo,
    edges: dict,
    scope: _Scope,
    axis: ast.AST,
    visited: set,
) -> bool:
    symbols, param_rooted = _axis_symbols(axis, scope, mod)
    if not symbols and not param_rooted:
        return True  # unresolvable expression — stay silent, not wrong
    bindings = mod.outer_bindings(scope)
    resolved = set(symbols)
    for s in list(symbols):
        resolved |= mod.resolve_to_strings(s)
    if resolved & bindings:
        return True
    if not param_rooted:
        return False
    # Obligation transfers to callers of the outermost enclosing fn.
    outer = scope.outermost()
    key = (outer.cls, getattr(outer.fn, "name", ""))
    if key in visited:
        return True  # recursion — give up quietly
    visited = visited | {key}
    param_names = [s for s in symbols if s in _all_params(scope)]
    callers = edges.get(key, []) + edges.get((None, key[1]), [])
    if not callers:
        return True  # public inner API — callers outside the repo bind it
    for caller_scope, call, _ in callers:
        for p in param_names:
            arg = _arg_for_param(call, outer, p)
            if arg is None:
                continue
            if not _axis_bound(mod, edges, caller_scope, arg, visited):
                return False
    return True


def _all_params(scope: _Scope) -> set[str]:
    out: set[str] = set()
    s: "_Scope | None" = scope
    while s is not None:
        out |= set(s.params)
        s = s.parent
    return out


def _dead_axis_index(
    mod: _ModuleInfo, scope: _Scope, call: ast.Call
) -> "int | None":
    """Line number of a dead axis_index, or None when consumed."""
    # Find the statement containing the call within the scope body.
    for stmt in ast.walk(scope.fn):
        if isinstance(stmt, ast.Expr) and _contains(stmt.value, call):
            if stmt.value is call:
                return call.lineno  # bare statement — dead
            return None  # part of a larger consumed expression
        if isinstance(stmt, ast.Assign) and _contains(stmt.value, call):
            if len(stmt.targets) == 1 and isinstance(
                stmt.targets[0], ast.Name
            ):
                name = stmt.targets[0].id
                # Consumed when the name is loaded anywhere in the
                # outermost function after binding (closures included).
                outer_fn = scope.outermost().fn
                for sub in ast.walk(outer_fn):
                    if (
                        isinstance(sub, ast.Name)
                        and sub.id == name
                        and isinstance(sub.ctx, ast.Load)
                    ):
                        return None
                return stmt.lineno
            return None
    return None  # used inline (return/condition/arithmetic) — consumed


def _contains(tree: ast.AST, node: ast.AST) -> bool:
    return any(sub is node for sub in ast.walk(tree))
