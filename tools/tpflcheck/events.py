"""Event-name drift lint: every flight-recorder span/event name emitted
anywhere in ``tpfl/`` must appear in ``docs/observability.md``.

The flight rings are the post-mortem surface — ``traceview`` timelines,
crash dumps, the ledger/quarantine joins — and their event taxonomy is
DOCUMENTED DATA (the span/event tables in docs/observability.md). A new
emission site that never lands in the doc rots the taxonomy silently:
the dump contains names no table explains. This lint closes the loop:

- **emitted** names are collected by AST walk over ``tpfl/``:
  ``flight.record(node, {... "name": "<literal>" ...})`` dict literals,
  and ``tracing.maybe_span("<literal>", ...)`` /
  ``tracing.event("<literal>", ...)`` call sites. Non-literal names
  (``"name": action`` variables, f-strings past their constant prefix)
  cannot be linted statically and are skipped — except f-strings with a
  constant ``prefix:`` head (``f"stage:{...}"``), which match a
  documented ``prefix:`` token.
- **documented** names are every backticked token in
  ``docs/observability.md`` (tables and prose both count — the doc's
  convention is that every taxonomy name renders as code).

Waivable like every check (``events:<name>`` keys) — for names that are
deliberately internal — so the taxonomy can evolve without the lint
blocking, but never silently.
"""

from __future__ import annotations

import ast
import pathlib
import re

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

DOC = "docs/observability.md"

_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _documented_names(root: pathlib.Path) -> set[str]:
    doc = root / DOC
    if not doc.exists():
        return set()
    # Per-line matching: an unbalanced backtick anywhere must not flip
    # every subsequent code-span pairing in the file.
    names: set[str] = set()
    for line in doc.read_text(encoding="utf-8").splitlines():
        names.update(_BACKTICK_RE.findall(line))
    return names


def _constant_prefix(node: ast.JoinedStr) -> "str | None":
    """The leading constant of an f-string when it names a taxonomy
    family (``f"stage:{...}"`` -> ``"stage:"``), else None."""
    if node.values and isinstance(node.values[0], ast.Constant):
        head = str(node.values[0].value)
        if ":" in head:
            return head.split(":", 1)[0] + ":"
    return None


def _emitted_names(
    root: pathlib.Path,
) -> "list[tuple[str, str, int]]":
    """[(name-or-prefix, file, line)] for every statically-visible
    span/event emission in tpfl/."""
    out: list[tuple[str, str, int]] = []
    for path in py_files(root):
        r = rel(root, path)
        tree = core.parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            name_node = None
            if (
                fn.attr == "record"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Dict)
            ):
                for k, v in zip(node.args[1].keys, node.args[1].values):
                    if isinstance(k, ast.Constant) and k.value == "name":
                        name_node = v
            elif (
                fn.attr in ("maybe_span", "event")
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "tracing"
                and node.args
            ):
                name_node = node.args[0]
            if name_node is None:
                continue
            if isinstance(name_node, ast.Constant):
                out.append((str(name_node.value), r, name_node.lineno))
            elif isinstance(name_node, ast.JoinedStr):
                prefix = _constant_prefix(name_node)
                if prefix is not None:
                    out.append((prefix, r, name_node.lineno))
    return out


def check_events(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    documented = _documented_names(root)
    # A documented `stage:<Name>` placeholder covers the `stage:`
    # prefix family; plain names match exactly.
    doc_prefixes = {d.split("<", 1)[0] for d in documented if "<" in d}
    out: list[Violation] = []
    for name, file, line in _emitted_names(root):
        if name in documented or name in doc_prefixes:
            continue
        out.append(
            Violation(
                "events", file, line,
                f"flight event/span name {name!r} is not documented in "
                f"{DOC} — add it to the span/event tables (or waive "
                "with a reason)",
                f"events:{name}",
            )
        )
    return out
