"""Architecture lint: the SURVEY layer map, enforced.

tpfl's layering (SURVEY §1, mirrored from the reference's):

    settings → management → communication → learning → parallel →
    models → simulation → stages → node/node_state → utils →
    attacks/interop → examples/cli

A module may import its own layer or anything BELOW it; an upward
module-level import is a violation. Two escape hatches are legal and
deliberately NOT flagged:

- ``if TYPE_CHECKING:`` imports (annotations only, no runtime edge) —
  how stages/commands name ``Node`` without depending on it;
- function-level imports (lazy seams, e.g. ``commands.py`` reaching
  into ``tpfl.learning.compression`` inside a handler) — a runtime
  edge, but one whose cost and cycle-safety the author chose
  explicitly. The lint pins the *static import graph*, which is what
  determines import-time cycles and layer erosion.
"""

from __future__ import annotations

import ast
import pathlib

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

#: Component -> layer number. A component is the first path element
#: under ``tpfl/`` (package dir or module stem).
LAYERS: dict[str, int] = {
    # foundations: stdlib-only (settings/exceptions/experiment) or
    # settings-only (concurrency)
    "__init__": 0,
    "settings": 0,
    "exceptions": 0,
    "experiment": 0,
    "concurrency": 0,
    "management": 1,
    "communication": 2,
    "learning": 3,
    "parallel": 4,
    "models": 5,
    "simulation": 6,
    "stages": 7,
    "node": 8,
    "node_state": 8,
    "utils": 9,
    "attacks": 10,
    "interop": 10,
    "examples": 11,
    "cli": 11,
}


def _component(module: str) -> "str | None":
    """'tpfl.communication.base' -> 'communication'; 'tpfl' -> '__init__'."""
    parts = module.split(".")
    if parts[0] != "tpfl":
        return None
    return parts[1] if len(parts) > 1 else "__init__"


def _file_component(relpath: str) -> "str | None":
    parts = pathlib.PurePosixPath(relpath).parts
    if parts[0] != "tpfl":
        return None
    if len(parts) == 2:
        return pathlib.PurePosixPath(parts[1]).stem
    return parts[1]


def _module_level_imports(tree: ast.Module) -> "list[tuple[str, int]]":
    """(module, lineno) for every import that creates a runtime edge at
    import time: module body plus try/if bodies at module level, but
    NOT ``if TYPE_CHECKING:`` bodies and NOT function/class bodies
    below method level."""
    out: list[tuple[str, int]] = []

    def is_type_checking(test: ast.expr) -> bool:
        return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
            isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
        )

    def walk(stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Import):
                out.extend((a.name, stmt.lineno) for a in stmt.names)
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.module and stmt.level == 0:
                    out.append((stmt.module, stmt.lineno))
            elif isinstance(stmt, ast.If):
                if not is_type_checking(stmt.test):
                    walk(stmt.body)
                walk(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body)
                for h in stmt.handlers:
                    walk(h.body)
                walk(stmt.orelse)
                walk(stmt.finalbody)

    walk(tree.body)
    return out


def check_layers(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    violations: list[Violation] = []
    for path in py_files(root):
        r = rel(root, path)
        comp = _file_component(r)
        if comp is None or comp not in LAYERS:
            violations.append(
                Violation(
                    "layers", r, 1,
                    f"component {comp!r} is not in the layer map "
                    "(add it to tools/tpflcheck/layers.py LAYERS)",
                    f"layers:{r}::unmapped",
                )
            )
            continue
        my_layer = LAYERS[comp]
        tree = core.parse(path)
        for module, lineno in _module_level_imports(tree):
            target = _component(module)
            if target is None:
                continue  # third-party / stdlib
            target_layer = LAYERS.get(target)
            if target_layer is None:
                violations.append(
                    Violation(
                        "layers", r, lineno,
                        f"import of unmapped component {module!r}",
                        f"layers:{r}::{module}",
                    )
                )
            elif target_layer > my_layer:
                violations.append(
                    Violation(
                        "layers", r, lineno,
                        f"upward import: {comp} (layer {my_layer}) "
                        f"imports {module} (layer {target_layer}) — "
                        "move the dependency down, invert it via a "
                        "callback, or make it a TYPE_CHECKING/"
                        "function-level seam",
                        f"layers:{r}::{module}",
                    )
                )
    return violations
