"""Settings-knob lint.

Four invariants over ``tpfl/settings.py``:

1. **Existence** — every ``Settings.X`` attribute reference in code
   (``tpfl/``, ``bench.py``, ``tools/``; AST-based, so docstring
   mentions don't count) names a declared knob. A typo'd knob
   silently reads as AttributeError at runtime, usually inside a
   rarely-exercised branch.
2. **Profile totality** — the three profile methods
   (``set_test_settings`` / ``set_standalone_settings`` /
   ``set_scale_settings``) must assign the SAME set of knobs. A knob
   tuned in one profile but not the others LEAKS across profile
   switches: ``set_scale_settings()`` arming ``AGGREGATION_STALL``
   and a later ``set_test_settings()`` not resetting it changes test
   behavior depending on call history — the class-level-mutable
   Settings design makes profiles correct only when they are total
   over the tuned set.
3. **Docs mention** — every declared knob appears by name somewhere in
   ``docs/*.md`` or ``README.md`` (the knob reference lives in
   docs/settings.md; this lint is what keeps it in sync).
4. **Unused knobs** are *reported* (returned as warnings, not
   violations): dead configuration is a maintenance smell but not a
   correctness bug.
"""

from __future__ import annotations

import ast
import pathlib

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

PROFILE_METHODS = (
    "set_test_settings",
    "set_standalone_settings",
    "set_scale_settings",
)


def _settings_decl(root: pathlib.Path) -> "tuple[set[str], dict[str, set[str]]]":
    """(declared knobs, profile method -> assigned knobs)."""
    path = root / "tpfl" / "settings.py"
    tree = core.parse(path)
    settings_cls = next(
        n
        for n in tree.body
        if isinstance(n, ast.ClassDef) and n.name == "Settings"
    )
    knobs: set[str] = set()
    profiles: dict[str, set[str]] = {}
    for node in settings_cls.body:
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        if isinstance(tgt, ast.Name) and tgt.id.isupper():
            knobs.add(tgt.id)
        if isinstance(node, ast.FunctionDef) and node.name in PROFILE_METHODS:
            assigned: set[str] = set()
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "cls"
                            and t.attr.isupper()
                        ):
                            assigned.add(t.attr)
            profiles[node.name] = assigned
    return knobs, profiles


def _referenced_knobs(root: pathlib.Path) -> dict[str, list[tuple[str, int]]]:
    """knob -> [(file, line)] for every ``Settings.X`` attribute access
    outside settings.py itself."""
    refs: dict[str, list[tuple[str, int]]] = {}
    files = py_files(root)
    for extra in ("bench.py",):
        p = root / extra
        if p.exists():
            files.append(p)
    tools_dir = root / "tools"
    if tools_dir.exists():
        files.extend(
            p
            for p in sorted(tools_dir.rglob("*.py"))
            if "__pycache__" not in p.parts and "perf" not in p.parts
        )
    for path in files:
        r = rel(root, path)
        if r == "tpfl/settings.py":
            continue
        tree = core.parse(path)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "Settings"
                and node.attr.isupper()
            ):
                refs.setdefault(node.attr, []).append((r, node.lineno))
    return refs


def _docs_text(root: pathlib.Path) -> str:
    chunks = []
    for p in sorted((root / "docs").glob("*.md")) if (root / "docs").exists() else []:
        chunks.append(p.read_text(encoding="utf-8"))
    readme = root / "README.md"
    if readme.exists():
        chunks.append(readme.read_text(encoding="utf-8"))
    return "\n".join(chunks)


def check_knobs(
    repo: "pathlib.Path | None" = None,
) -> "tuple[list[Violation], list[str]]":
    """Returns (violations, warnings). Warnings are the unused-knob
    report — informational, never a failure."""
    root = repo_root(repo)
    violations: list[Violation] = []
    warnings: list[str] = []
    knobs, profiles = _settings_decl(root)
    refs = _referenced_knobs(root)

    # 1. existence
    for name, sites in sorted(refs.items()):
        if name not in knobs:
            f, line = sites[0]
            violations.append(
                Violation(
                    "knobs", f, line,
                    f"Settings.{name} referenced but not declared in "
                    "tpfl/settings.py"
                    + (f" (+{len(sites) - 1} more sites)" if len(sites) > 1 else ""),
                    f"knobs:undeclared:{name}",
                )
            )

    # 2. profile totality
    if profiles:
        union: set[str] = set()
        for assigned in profiles.values():
            union |= assigned
        for method in PROFILE_METHODS:
            assigned = profiles.get(method, set())
            for name in sorted(assigned - knobs):
                violations.append(
                    Violation(
                        "knobs", "tpfl/settings.py", 0,
                        f"{method} assigns unknown knob {name}",
                        f"knobs:unknown:{method}:{name}",
                    )
                )
            missing = sorted(union - assigned)
            if missing:
                violations.append(
                    Violation(
                        "knobs", "tpfl/settings.py", 0,
                        f"{method} does not assign {missing} — profiles "
                        "must be total over the tuned-knob union, or "
                        "values leak across profile switches",
                        f"knobs:partial:{method}",
                    )
                )

    # 3. docs mention
    docs = _docs_text(root)
    for name in sorted(knobs):
        if name not in docs:
            violations.append(
                Violation(
                    "knobs", "tpfl/settings.py", 0,
                    f"knob {name} is not mentioned anywhere in docs/ or "
                    "README.md (add it to docs/settings.md)",
                    f"knobs:undocumented:{name}",
                )
            )

    # 4. unused report (warnings only)
    for name in sorted(knobs - set(refs)):
        warnings.append(
            f"knob Settings.{name} is declared but never referenced in "
            "tpfl/, bench.py, or tools/"
        )
    return violations, warnings
