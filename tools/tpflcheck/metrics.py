"""Metric-name drift lint: every ``tpfl_*`` series name registered
anywhere in ``tpfl/`` must appear in ``docs/observability.md``.

The events lint's contract, extended to the registry plane: the metric
taxonomy is DOCUMENTED DATA (the per-plane series tables in
docs/observability.md — what scrapes, dashboards and the bench gates
key on), and a new ``metrics.counter/gauge/observe`` site whose name
never lands in the doc rots it silently. This pass closes the loop:

- **emitted** names are collected by AST walk over ``tpfl/``: the
  first argument of any ``.counter(...)`` / ``.gauge(...)`` /
  ``.observe(...)`` call when it is a ``"tpfl_"``-prefixed string
  literal — receiver-agnostic on purpose (the module singleton
  ``metrics``, ``telemetry.metrics``, a bound registry all count);
  the ``tpfl_`` prefix is what keeps unrelated ``.counter()`` methods
  out. F-strings with a constant ``tpfl_``-head
  (``f"tpfl_system_{metric}"``) lint as a name PREFIX.
- **documented** names are every backticked ``tpfl_*`` token in
  ``docs/observability.md``, with the doc's two compression
  conventions expanded: a brace FAMILY after a trailing underscore
  (``tpfl_engine_{loss,delta_norm}`` → both full names; a ``*``-tailed
  member like ``net_*`` becomes a prefix) vs a LABEL annotation after
  a full name (``tpfl_mfu{program}`` → ``tpfl_mfu``), and a trailing
  ``*`` wildcard (``tpfl_contrib_*``) covering the whole prefix.

Waivable like every check (``metrics:<name>`` keys) for deliberately
internal series — the taxonomy can evolve without the lint blocking,
but never silently.
"""

from __future__ import annotations

import ast
import pathlib
import re

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

DOC = "docs/observability.md"

_BACKTICK_RE = re.compile(r"`([^`]+)`")

_REGISTRY_CALLS = ("counter", "gauge", "observe")


def _documented_names(
    root: pathlib.Path,
) -> "tuple[set[str], set[str]]":
    """(exact names, wildcard prefixes) from the doc's backticked
    ``tpfl_*`` tokens, brace families and ``*`` wildcards expanded."""
    doc = root / DOC
    exact: set[str] = set()
    prefixes: set[str] = set()
    if not doc.exists():
        return exact, prefixes
    # Per-line matching, like the events lint: one unbalanced backtick
    # must not flip every subsequent code-span pairing.
    tokens: set[str] = set()
    for line in doc.read_text(encoding="utf-8").splitlines():
        tokens.update(
            t for t in _BACKTICK_RE.findall(line) if t.startswith("tpfl_")
        )
    for tok in tokens:
        head, brace, rest = tok.partition("{")
        if brace and head.endswith("_"):
            # Family: tpfl_engine_{loss,delta_norm} — each member is a
            # full name; a *-tailed member is a prefix.
            for member in rest.rstrip("}").split(","):
                member = member.strip()
                if member.endswith("*"):
                    prefixes.add(head + member[:-1])
                elif member:
                    exact.add(head + member)
            continue
        if brace:
            # Label annotation: tpfl_mfu{program} — the braces name
            # the series' labels, not sibling metrics.
            tok = head
        if tok.endswith("*"):
            prefixes.add(tok[:-1])
        else:
            exact.add(tok)
    return exact, prefixes


def _constant_head(node: ast.JoinedStr) -> "str | None":
    """The leading constant of an f-string metric name
    (``f"tpfl_system_{metric}"`` → ``"tpfl_system_"``), else None."""
    if node.values and isinstance(node.values[0], ast.Constant):
        head = str(node.values[0].value)
        if head.startswith("tpfl_"):
            return head
    return None


def _emitted_names(
    root: pathlib.Path,
) -> "list[tuple[str, bool, str, int]]":
    """[(name, is_prefix, file, line)] for every statically-visible
    ``tpfl_*`` registry call in tpfl/."""
    out: list[tuple[str, bool, str, int]] = []
    for path in py_files(root):
        r = rel(root, path)
        tree = core.parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if (
                not isinstance(fn, ast.Attribute)
                or fn.attr not in _REGISTRY_CALLS
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value.startswith("tpfl_"):
                    out.append((arg.value, False, r, arg.lineno))
            elif isinstance(arg, ast.JoinedStr):
                head = _constant_head(arg)
                if head is not None:
                    out.append((head, True, r, arg.lineno))
    return out


def check_metrics(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    exact, prefixes = _documented_names(root)
    out: list[Violation] = []
    for name, is_prefix, file, line in _emitted_names(root):
        if is_prefix:
            # A family head is documented when any doc name lives
            # under it, or a doc wildcard overlaps it either way.
            ok = any(e.startswith(name) for e in exact) or any(
                p.startswith(name) or name.startswith(p) for p in prefixes
            )
        else:
            ok = name in exact or any(
                name.startswith(p) for p in prefixes
            )
        if ok:
            continue
        kind = "metric-name family" if is_prefix else "metric name"
        out.append(
            Violation(
                "metrics", file, line,
                f"{kind} {name!r} is registered here but not documented "
                f"in {DOC} — add it to the series tables (or waive with "
                "a reason)",
                f"metrics:{name}",
            )
        )
    return out
