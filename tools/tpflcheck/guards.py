"""Guarded-by race lint.

The threaded core's shared fields are declared with source annotations
(on the assignment line in ``__init__``, or the comment line directly
above it)::

    # guarded-by: models_aggregated_lock
    self.models_aggregated: dict[str, list[str]] = {}

    # unguarded: replaced wholesale by the learning thread; readers
    # iterate whichever snapshot reference they loaded.
    self.train_set: list[str] = []

Grammar:

- ``# guarded-by: <lock>`` — every read/write of the attribute,
  ANYWHERE under ``tpfl/``, must sit lexically inside a
  ``with <...>.<lock>:`` block in the same function scope.
- ``# guarded-by: <lock> writes`` — only writes are checked; lock-free
  reads are declared tolerable (monotonic watermarks, cache keys whose
  staleness is benign). The write sites are the read-modify-writes
  that actually lose updates.
- ``# unguarded: <reason>`` — explicitly waived at the declaration,
  with a mandatory reason (GIL-atomic reference swaps, internally
  synchronized objects).

Two passes over :data:`GUARDED_MODULES` (the modules owning the
cross-thread state — NodeState, Gossiper, Neighbors, CircuitBreaker,
BufferPool, the metric stores, the Aggregator):

1. **Completeness** — every attribute initialized in ``__init__`` with
   a mutable container (dict/list/set/deque literal or constructor)
   must carry an annotation. New shared state cannot be added
   unannotated.
2. **Access** — every access to a guarded attribute, across ALL of
   ``tpfl/`` (the expected true positives historically lived in
   ``stages/base_node.py``, not in the owning module), is checked for
   an enclosing ``with`` on the declared lock. Helpers that run under
   the caller's lock are waived in ``pyproject.toml``
   (``guards:<file>::<qualname>::*``) with the reason in the data.

Lexical containment deliberately does NOT cross function boundaries: a
closure defined inside a ``with`` block but called later is not
protected by it, so the lint treats it as unguarded.
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root

#: The modules whose classes own cross-thread mutable state.
GUARDED_MODULES = (
    "tpfl/node_state.py",
    "tpfl/communication/gossiper.py",
    "tpfl/communication/neighbors.py",
    "tpfl/communication/resilience.py",
    "tpfl/learning/bufferpool.py",
    "tpfl/management/metric_storage.py",
    "tpfl/management/logger.py",
    "tpfl/management/ledger.py",
    "tpfl/management/node_monitor.py",
    "tpfl/management/profiling.py",
    "tpfl/management/telemetry.py",
    "tpfl/management/tracing.py",
    "tpfl/management/quarantine.py",
    "tpfl/learning/aggregators/aggregator.py",
    "tpfl/learning/aggregators/robust.py",
    "tpfl/learning/async_control.py",
    "tpfl/attacks/attacks.py",
    "tpfl/attacks/plan.py",
    "tpfl/parallel/engine.py",
    "tpfl/parallel/membership.py",
    "tpfl/parallel/window_pipeline.py",
    "tpfl/management/checkpoint.py",
)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)(\s+writes)?")
_UNGUARDED_RE = re.compile(r"#\s*unguarded:\s*(\S.*)?$")

_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict", "OrderedDict"}
_LOCKISH_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore", "make_lock",
    "TracedLock",
}


@dataclass
class GuardDecl:
    module: str  # repo-relative path of the owning module
    cls: str
    attr: str
    lock: "str | None"  # None => unguarded (annotated waiver)
    writes_only: bool
    reason: "str | None"
    line: int


def _annotation_for(lines: list[str], lineno: int) -> "tuple[str, str, bool] | None":
    """Look for a guard annotation on ``lineno`` (1-based) or in the
    contiguous comment block directly above it. Returns
    (kind, payload, writes_only) where kind is 'guarded'/'unguarded'."""
    candidates = [lines[lineno - 1]]
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        candidates.append(lines[i])
        i -= 1
    for text in candidates:
        m = _GUARDED_RE.search(text)
        if m:
            return ("guarded", m.group(1), bool(m.group(2)))
        m = _UNGUARDED_RE.search(text)
        if m:
            return ("unguarded", (m.group(1) or "").strip(), False)
    return None


def _is_mutable_init(value: ast.expr) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else ""
        )
        return name in _MUTABLE_CTORS
    return False


def _is_lockish_init(value: ast.expr) -> bool:
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else ""
        )
        return name in _LOCKISH_CTORS
    return False


def collect_decls(
    root: pathlib.Path,
) -> "tuple[list[GuardDecl], list[Violation]]":
    """Parse annotations out of the guarded modules; also run the
    completeness pass (unannotated mutable ``__init__`` attributes)."""
    decls: list[GuardDecl] = []
    violations: list[Violation] = []
    for module in GUARDED_MODULES:
        path = root / module
        if not path.exists():
            continue
        src = core.source(path)
        lines = src.splitlines()
        tree = core.parse(path)
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            init = next(
                (
                    f
                    for f in cls.body
                    if isinstance(f, ast.FunctionDef) and f.name == "__init__"
                ),
                None,
            )
            if init is None:
                continue
            for stmt in ast.walk(init):
                if isinstance(stmt, ast.Assign):
                    targets, value = stmt.targets, stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    targets, value = [stmt.target], stmt.value
                else:
                    continue
                for t in targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    ann = _annotation_for(lines, stmt.lineno)
                    if ann is not None:
                        kind, payload, writes_only = ann
                        if kind == "guarded":
                            decls.append(
                                GuardDecl(
                                    module, cls.name, t.attr, payload,
                                    writes_only, None, stmt.lineno,
                                )
                            )
                        else:
                            if not payload:
                                violations.append(
                                    Violation(
                                        "guards", module, stmt.lineno,
                                        f"{cls.name}.{t.attr}: '# unguarded:' "
                                        "annotation requires a reason",
                                        f"guards:{module}::{cls.name}.{t.attr}"
                                        "::reason",
                                    )
                                )
                            decls.append(
                                GuardDecl(
                                    module, cls.name, t.attr, None, False,
                                    payload or None, stmt.lineno,
                                )
                            )
                    elif _is_mutable_init(value) and not _is_lockish_init(value):
                        violations.append(
                            Violation(
                                "guards", module, stmt.lineno,
                                f"{cls.name}.{t.attr}: mutable attribute "
                                "without a '# guarded-by:' / "
                                "'# unguarded:' annotation",
                                f"guards:{module}::{cls.name}.{t.attr}"
                                "::unannotated",
                            )
                        )
    return decls, violations


class _AccessChecker(ast.NodeVisitor):
    """Walk one file tracking (qualname scope, held-lock with-stack)."""

    def __init__(
        self,
        relpath: str,
        guarded: dict[str, list[GuardDecl]],
        violations: list[Violation],
    ) -> None:
        self.relpath = relpath
        self.guarded = guarded
        self.violations = violations
        self.scope: list[str] = []
        # With-held lock attr names, per function scope depth.
        self.held: list[set[str]] = [set()]

    # --- scope tracking ---

    def _enter_fn(self, node: "ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda") -> None:
        name = getattr(node, "name", "<lambda>")
        self.scope.append(name)
        self.held.append(set())  # a with outside the fn doesn't protect it
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.held.pop()
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_fn(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_fn(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_fn(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_With(self, node: ast.With) -> None:
        names = set()
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Attribute):
                names.add(expr.attr)
            elif isinstance(expr, ast.Name):
                names.add(expr.id)
            # The with-item expression itself is OUTSIDE the lock.
            self.visit(expr)
        self.held[-1] |= names
        for stmt in node.body:
            self.visit(stmt)
        self.held[-1] -= names

    # --- the check ---

    def visit_Attribute(self, node: ast.Attribute) -> None:
        decls = self.guarded.get(node.attr)
        if decls:
            is_write = not isinstance(node.ctx, ast.Load)
            applicable = [
                d for d in decls if is_write or not d.writes_only
            ]
            if applicable:
                locks = {d.lock for d in applicable}
                if not (locks & self.held[-1]):
                    qual = ".".join(self.scope) or "<module>"
                    owner = applicable[0]
                    # Auto-exempt the declaring __init__ of ANY owning
                    # class (the object is not shared until the
                    # constructor returns).
                    in_owner_init = (
                        self.scope
                        and self.scope[-1] == "__init__"
                        and any(
                            self.relpath == d.module and d.cls in self.scope
                            for d in applicable
                        )
                    )
                    if not in_owner_init:
                        kind = "write" if is_write else "read"
                        self.violations.append(
                            Violation(
                                "guards", self.relpath, node.lineno,
                                f"{kind} of {owner.cls}.{node.attr} "
                                f"(guarded by {sorted(locks)[0]}) outside "
                                f"'with {sorted(locks)[0]}:' in {qual}",
                                f"guards:{self.relpath}::{qual}::{node.attr}",
                            )
                        )
        self.generic_visit(node)


def check_guards(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    decls, violations = collect_decls(root)
    guarded: dict[str, list[GuardDecl]] = {}
    for d in decls:
        if d.lock is not None:
            guarded.setdefault(d.attr, []).append(d)
    for path in py_files(root):
        r = rel(root, path)
        tree = core.parse(path)
        _AccessChecker(r, guarded, violations).visit(tree)
    return violations
