"""Host-sync lint: implicit device→host syncs on the hot path.

On TPU every ``.item()``, ``float(device_array)``, ``np.asarray(...)``
of a device value, and bare ``block_until_ready`` stalls the host on
the device queue — the async dispatch pipeline that hides the ~67 ms
RTT collapses, and one stray debug cast costs a whole round of
overlap. The profiling observatory measures these gaps
(``tpfl_round_attr_seconds`` dispatch vs train); this lint keeps new
ones from creeping into the modules where the gap is the product.

Scope: :data:`HOT_PATHS` — the engine round dispatch, the vmapped
federation, the learner fit/eval seams, the batched-fit pool, and the
aggregator eager-fold family. Flags, per function scope:

1. ``<expr>.item()`` — always a sync.
2. ``jax.block_until_ready(...)`` / ``<expr>.block_until_ready()``.
3. ``np.asarray(x)`` / ``np.array(x)`` where ``x`` is a bare
   name/attribute/subscript (a device-value candidate; literals and
   comprehensions are host data).
4. ``float(x)`` / ``int(x)`` / ``bool(x)`` where ``x``'s root name is
   **device-tracked**: bound (possibly via tuple-unpacking) from a
   call of a compiled-program callable — a name that is exactly
   ``fn`` or ends in ``_fn`` / ``_program`` / ``.run_rounds`` /
   ``.evaluate`` (the repo's program-handle naming convention, which
   the capture pass's cache-getter discipline reinforces). Re-binding
   a tracked name from ``np.asarray(...)`` UN-tracks it: that line is
   the one accounted sync, everything after reads host memory.

Exemptions:

- a sync inside an ``if``/``while`` whose condition mentions an
  observability gate (``prof``, ``tele``, profiling / telemetry /
  ledger knobs, ``...enabled()``, debug-level checks) — gated
  measurement taps are the sanctioned pattern: zero syncs when off;
- ``# host-sync: <reason>`` on the line (or the comment block above)
  — for deliberate syncs at consumption boundaries (eval metrics,
  end-of-chunk result folds), with the reason as reviewable data.

Waiver keys: ``sync:<file>:<line>``.
"""

from __future__ import annotations

import ast
import pathlib
import re

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, repo_root

#: The hot-path roster: modules where a stray sync costs round overlap.
HOT_PATHS = (
    "tpfl/parallel/engine.py",
    "tpfl/parallel/federation.py",
    "tpfl/parallel/federation_learner.py",
    "tpfl/parallel/window_pipeline.py",
    "tpfl/learning/jax_learner.py",
    "tpfl/simulation/batched_fit.py",
    "tpfl/learning/aggregators/aggregator.py",
    "tpfl/learning/aggregators/fedavg.py",
    "tpfl/learning/aggregators/fedmedian.py",
    "tpfl/learning/aggregators/robust.py",
    "tpfl/learning/aggregators/scaffold.py",
)

_ANNOT_RE = re.compile(r"#\s*host-sync:\s*(\S.*)$")
_GATE_RE = re.compile(
    r"prof|tele|ledger|LEDGER|PROFIL|TELEMETRY|DEBUG|debug|enabled|verbose"
)

#: Callee name shapes whose results are device arrays (the compiled-
#: program handle convention: `fn = cache[key]; out = fn(...)`).
_PROGRAM_CALLEES = re.compile(r"(^fn$|_fn$|_program$|^run_rounds$|^evaluate$)")

_CASTS = {"float", "int", "bool"}
_NP_NAMES = {"np", "numpy"}


def _annotated(lines: list[str], lineno: int) -> bool:
    candidates = [lines[lineno - 1]]
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        candidates.append(lines[i])
        i -= 1
    return any(_ANNOT_RE.search(text) for text in candidates)


def _root_name(expr: ast.AST) -> "str | None":
    while isinstance(expr, (ast.Subscript, ast.Attribute)):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _callee_terminal(call: ast.Call) -> "str | None":
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class _FnChecker:
    def __init__(
        self, relpath: str, fn: ast.AST, lines: list[str]
    ) -> None:
        self.r = relpath
        self.fn = fn
        self.lines = lines
        self.tracked: set[str] = set()
        self.violations: list[Violation] = []
        # gate stack: are we under an observability-gated branch?
        self._gates = 0

    # --- helpers ---

    def _flag(self, lineno: int, what: str, hint: str) -> None:
        if self._gates > 0 or _annotated(self.lines, lineno):
            return
        self.violations.append(
            Violation(
                "sync", self.r, lineno,
                f"{what} on the hot path forces a device→host sync "
                f"(stalls the async dispatch pipeline) — {hint}, gate "
                "it behind an observability knob, or annotate "
                "'# host-sync: <reason>'",
                f"sync:{self.r}:{lineno}",
            )
        )

    def _is_gate(self, test: ast.AST) -> bool:
        try:
            src = ast.unparse(test)
        except Exception:
            return False
        return bool(_GATE_RE.search(src))

    # --- walk ---

    def run(self) -> None:
        for stmt in ast.iter_child_nodes(self.fn):
            self._stmt(stmt)

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scope: its own checker run covers it
        if isinstance(node, (ast.If, ast.While)):
            self._expr(node.test)
            gated = self._is_gate(node.test)
            if gated:
                self._gates += 1
            for sub in node.body:
                self._stmt(sub)
            if gated:
                self._gates -= 1
            for sub in node.orelse:
                self._stmt(sub)
            return
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            self._track_assign(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            else:
                self._stmt(child)

    def _track_assign(self, node: ast.Assign) -> None:
        val = node.value
        from_program = (
            isinstance(val, ast.Call)
            and (t := _callee_terminal(val)) is not None
            and _PROGRAM_CALLEES.search(t) is not None
        )
        # np.asarray(...) re-bind: the value is host now.
        to_host = (
            isinstance(val, ast.Call)
            and _callee_terminal(val) in ("asarray", "array")
            and isinstance(val.func, ast.Attribute)
            and isinstance(val.func.value, ast.Name)
            and val.func.value.id in _NP_NAMES
        )
        from_tracked = (
            isinstance(val, ast.Name) and val.id in self.tracked
        )
        targets: list[str] = []
        for t_ in node.targets:
            if isinstance(t_, ast.Name):
                targets.append(t_.id)
            elif isinstance(t_, ast.Tuple):
                targets.extend(
                    e.id for e in t_.elts if isinstance(e, ast.Name)
                )
        for name in targets:
            if to_host:
                self.tracked.discard(name)
            elif from_program or from_tracked:
                self.tracked.add(name)
            else:
                self.tracked.discard(name)

    def _expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(
                sub, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if not isinstance(sub, ast.Call):
                continue
            term = _callee_terminal(sub)
            if term == "item" and isinstance(sub.func, ast.Attribute):
                self._flag(
                    sub.lineno, ".item()",
                    "batch scalars into one fetch",
                )
            elif term == "block_until_ready":
                self._flag(
                    sub.lineno, "block_until_ready",
                    "let the async dispatch run ahead",
                )
            elif (
                term in ("asarray", "array")
                and isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id in _NP_NAMES
                and sub.args
                and isinstance(
                    sub.args[0], (ast.Name, ast.Attribute, ast.Subscript)
                )
            ):
                self._flag(
                    sub.lineno, f"np.{term}(...) of a device value",
                    "keep it on device (jnp) or sync once at the "
                    "consumption boundary",
                )
            elif (
                isinstance(sub.func, ast.Name)
                and sub.func.id in _CASTS
                and sub.args
            ):
                root = _root_name(sub.args[0])
                if root is not None and root in self.tracked:
                    self._flag(
                        sub.lineno,
                        f"{sub.func.id}() of a compiled-program result",
                        "the cast blocks on the device queue",
                    )


def check_sync(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    violations: list[Violation] = []
    for relpath in HOT_PATHS:
        path = root / relpath
        if not path.exists():
            continue
        try:
            src = core.source(path)
            tree = core.parse(path)
        except SyntaxError:
            continue
        lines = src.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                checker = _FnChecker(relpath, node, lines)
                checker.run()
                violations.extend(checker.violations)
    uniq: dict[str, Violation] = {}
    for v in violations:
        uniq.setdefault(v.key, v)
    return list(uniq.values())
