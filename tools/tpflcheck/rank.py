"""Multi-host divergence lint: rank-gated program dispatch.

On a multi-process (3D ``hosts``-axis) mesh, every process must issue
the IDENTICAL sequence of compiled SPMD programs and collectives —
GSPMD's contract. A branch on ``jax.process_index()`` (or anything
derived from it) around a dispatch means rank 0 enters a collective
rank 1 never reaches: the fleet hangs on DCN with no error, the single
worst failure mode the cross-host engine has. ``process_count()`` is
uniform in a healthy world but joins the taint set anyway — a value
derived from either marks host-identity-dependent control flow, and
review must see every place it gates device work.

The pass walks the crosshost roster (:data:`ROSTER`) and flags any
statement that **dispatches a compiled program or issues a collective**
while lexically gated by a rank-derived condition (``if`` / ``while``
/ ternary / ``and``-``or`` short-circuit). "Rank-derived" propagates
through assignments within a function and ONE level of call
resolution (like ``locks.py``): a call to a roster function whose body
reads ``process_index``/``process_count`` (``is_multiprocess``,
``ensure_distributed``, ``resolve_shard_hosts``) taints its result.
"Dispatches" is the program-handle naming convention the sync pass
enforces (``fn`` / ``*_fn`` / ``*_program`` / ``run_rounds`` /
``evaluate`` / ``dispatch_window``), the named collectives
(``psum`` / ``all_gather`` / ...), and — one hop deep — any roster
function whose body contains one.

Escape: ``# rank-dependent: <reason>`` on the dispatch line (or the
contiguous comment block above it, or on the gating ``if`` itself) —
for deliberately rank-local work (receipt writing, host-local logging,
the crosshost fork harness) with the reason as reviewable data.

Runtime half: ``Settings.RANK_CONTRACTS``
(:mod:`tpfl.parallel.ranksafe`) — every engine dispatch appends the
digest of its program cache key + lowered-HLO fingerprint to an
ordered per-process log; ``crosshost.launch`` compares the receipts
across ranks and fails with the first divergent (rank, ordinal, key)
witness. The static pass proves gate discipline at review time; the
receipts catch what it cannot (data-dependent divergence through
dynamic dispatch).

Waiver keys: ``rank:<file>:<line>``.
"""

from __future__ import annotations

import ast
import pathlib
import re

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, repo_root

#: The crosshost roster: every module that builds or drives the
#: multi-process engine path.
ROSTER = (
    "tpfl/parallel/engine.py",
    "tpfl/parallel/distributed.py",
    "tpfl/parallel/crosshost.py",
    "tpfl/parallel/window_pipeline.py",
    "tpfl/parallel/population.py",
)

_RANK_SOURCES = {"process_index", "process_count"}

#: Compiled-program handle names (the sync pass's convention) plus the
#: window dispatch entry points.
_DISPATCH_RE = re.compile(
    r"(^fn$|_fn$|_program$|^run_rounds$|^evaluate$|^dispatch_window$)"
)
_COLLECTIVES = {
    "psum", "psum_scatter", "all_gather", "all_to_all", "pmean",
    "pmax", "pmin", "ppermute",
}

_ANNOT_RE = re.compile(r"#\s*rank-dependent:\s*(\S.*)$")


def _annotated(lines: "list[str]", lineno: int) -> bool:
    candidates = [lines[lineno - 1]]
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        candidates.append(lines[i])
        i -= 1
    return any(_ANNOT_RE.search(text) for text in candidates)


def _terminal(call: ast.Call) -> "str | None":
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class _Index:
    """One-hop call-resolution summaries over every roster module: for
    each function/method, does it derive a value from ``process_*``,
    and does its body dispatch a program or collective?"""

    def __init__(self) -> None:
        self.rank_derived: set[str] = set()
        self.dispatches: set[str] = set()

    @classmethod
    def build(cls, trees: "list[ast.Module]") -> "_Index":
        idx = cls()
        for tree in trees:
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                reads_rank = False
                dispatches = False
                returns = False
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        term = _terminal(sub)
                        if term in _RANK_SOURCES:
                            reads_rank = True
                        elif term is not None and (
                            _DISPATCH_RE.search(term) or term in _COLLECTIVES
                        ):
                            dispatches = True
                    elif isinstance(sub, ast.Return) and sub.value is not None:
                        returns = True
                if reads_rank and returns:
                    idx.rank_derived.add(node.name)
                if dispatches:
                    idx.dispatches.add(node.name)
        return idx


class _FnChecker:
    def __init__(
        self, relpath: str, fn: ast.AST, lines: "list[str]", index: _Index
    ) -> None:
        self.r = relpath
        self.fn = fn
        self.lines = lines
        self.index = index
        self.tracked: set[str] = set()
        self.violations: list[Violation] = []
        self._gates = 0  # rank-derived gate nesting depth
        self._gate_exempt = 0  # gates carrying their own annotation

    # --- taint ---

    def _rank_expr(self, expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in self.tracked:
                return True
            if isinstance(sub, ast.Call):
                term = _terminal(sub)
                if term in _RANK_SOURCES or term in self.index.rank_derived:
                    return True
        return False

    def _track_assign(self, node: ast.Assign) -> None:
        tainted = self._rank_expr(node.value)
        targets: list[str] = []
        for t in node.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
            elif isinstance(t, ast.Tuple):
                targets.extend(
                    e.id for e in t.elts if isinstance(e, ast.Name)
                )
        for name in targets:
            (self.tracked.add if tainted else self.tracked.discard)(name)

    # --- dispatch detection ---

    def _is_dispatch(self, call: ast.Call) -> "str | None":
        term = _terminal(call)
        if term is None:
            return None
        if _DISPATCH_RE.search(term) or term in _COLLECTIVES:
            return term
        # One hop: a bare or self.<method> call to a roster function
        # whose own body dispatches.
        if term in self.index.dispatches:
            return term
        return None

    def _flag_dispatches(self, node: ast.AST) -> None:
        """Flag every dispatch call lexically under ``node``."""
        for sub in ast.walk(node):
            if isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if not isinstance(sub, ast.Call):
                continue
            name = self._is_dispatch(sub)
            if name is None:
                continue
            if self._gate_exempt > 0 or _annotated(self.lines, sub.lineno):
                continue
            self.violations.append(
                Violation(
                    "rank", self.r, sub.lineno,
                    f"dispatch of {name!r} is gated by a rank-derived "
                    "condition (jax.process_index/process_count) — every "
                    "process must issue the identical program sequence "
                    "or the fleet hangs on the first collective; lift "
                    "the dispatch out of the branch or annotate "
                    "'# rank-dependent: <reason>'",
                    f"rank:{self.r}:{sub.lineno}",
                )
            )

    # --- walk ---

    def run(self) -> None:
        for stmt in ast.iter_child_nodes(self.fn):
            self._stmt(stmt)

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scope: its own checker run covers it
        if isinstance(node, (ast.If, ast.While)):
            self._expr(node.test)
            gated = self._rank_expr(node.test)
            exempt = gated and _annotated(self.lines, node.lineno)
            if gated:
                self._gates += 1
                if exempt:
                    self._gate_exempt += 1
            # BOTH branches run rank-dependently once the test is
            # rank-derived — the else arm is the ranks the if skipped.
            for sub in node.body:
                self._stmt(sub)
            for sub in node.orelse:
                self._stmt(sub)
            if gated:
                self._gates -= 1
                if exempt:
                    self._gate_exempt -= 1
            return
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            self._track_assign(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            else:
                self._stmt(child)

    def _expr(self, node: ast.AST) -> None:
        if self._gates > 0:
            self._flag_dispatches(node)
        for sub in ast.walk(node):
            if isinstance(
                sub, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            # Ternary: `fn(...) if rank == 0 else ...` — both arms are
            # rank-gated once the test is.
            if isinstance(sub, ast.IfExp) and self._rank_expr(sub.test):
                self._flag_dispatches(sub.body)
                self._flag_dispatches(sub.orelse)
            # Short-circuit: `rank == 0 and fn(...)` — operands after a
            # rank-derived one only evaluate on some ranks.
            elif isinstance(sub, ast.BoolOp):
                tainted = False
                for operand in sub.values:
                    if tainted:
                        self._flag_dispatches(operand)
                    elif self._rank_expr(operand):
                        tainted = True


def check_rank(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    sources: list[tuple[str, str, ast.Module]] = []
    for relpath in ROSTER:
        path = root / relpath
        if not path.exists():
            continue
        try:
            src = core.source(path)
            tree = core.parse(path)
        except SyntaxError:
            continue
        sources.append((relpath, src, tree))
    index = _Index.build([t for _, _, t in sources])
    violations: list[Violation] = []
    for relpath, src, tree in sources:
        lines = src.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                checker = _FnChecker(relpath, node, lines, index)
                checker.run()
                violations.extend(checker.violations)
    uniq: dict[str, Violation] = {}
    for v in violations:
        uniq.setdefault(v.key, v)
    return list(uniq.values())
