"""tpflcheck — tpfl's static concurrency & invariant analysis suite.

One framework: shared file-walking / waiver / reporting machinery
(``core.py``), fifteen checks::

    guards    guarded-by race lint (# guarded-by: annotations)
    locks     static lock-order extraction + deadlock (cycle) detection
    capture   trace-capture totality (a Settings knob a traced program
              body reads must be an axis of its cache key, cache-getter
              key tuples must be total over their parameters, and
              dispatch-resolved knobs must reach the key — the stale-
              compiled-program bug class; runtime half:
              Settings.TRACE_CONTRACTS)
    spmd      SPMD collective/axis lint (psum/all_gather/axis_index
              axis names must be bound by an enclosing shard_map/vmap/
              pmap; a dead axis_index is the PR-10 partitioner bug)
    sync      host-sync lint (.item(), float()/np.asarray of device
              values, bare block_until_ready on hot-path modules must
              be observability-gated or '# host-sync:' annotated)
    donate    donated-buffer reuse lint (a jax.jit donate_argnums
              binding must not be read after the dispatch that
              consumed it — re-bind from the program's outputs)
    layers    SURVEY layer map (no upward module-level imports)
    knobs     Settings knob existence / profile totality / docs sync
    threads   thread-lifecycle hygiene (name= + daemon= everywhere)
    trace     timing/logging-path lint (no time.time() or raw logging
              outside tpfl/management — spans/metrics are the only
              sanctioned timing path; see docs/observability.md)
    events    event-name drift lint (every flight span/event name
              emitted in tpfl/ must appear in docs/observability.md's
              taxonomy tables — waivable)
    metrics   metric-name drift lint (every tpfl_* series name a
              counter/gauge/observe call registers must appear in
              docs/observability.md's series tables — waivable)
    wire      codec-registry, copy-discipline and RPC-path lints
              (the original wirecheck trio)
    state     checkpoint-state totality (every mutable field of the
              export_state/state_export roster is exported or
              '# ephemeral:'-annotated; export/import key-set
              symmetry; runtime half: Settings.STATE_CONTRACTS)
    rank      multi-host divergence lint (no compiled-program dispatch
              or collective gated on jax.process_index/process_count-
              derived values unless '# rank-dependent:'-annotated;
              runtime half: Settings.RANK_CONTRACTS dispatch receipts)

Run: ``python -m tools.tpflcheck`` (exit 1 on any unwaived violation).
Waivers are data in ``pyproject.toml`` (``[tool.tpflcheck]``), each
with a mandatory reason. The runtime counterpart of the ``locks``
check is ``Settings.LOCK_TRACING`` (``tpfl.concurrency``). See
docs/concurrency.md.
"""

from __future__ import annotations

import pathlib

from tools.tpflcheck import wire
from tools.tpflcheck.core import (
    Violation,
    Waivers,
    apply_waivers,
    load_waivers,
    repo_root,
)
from tools.tpflcheck.capture import check_capture
from tools.tpflcheck.donate import check_donate
from tools.tpflcheck.events import check_events
from tools.tpflcheck.guards import check_guards
from tools.tpflcheck.knobs import check_knobs
from tools.tpflcheck.layers import check_layers
from tools.tpflcheck.locks import check_locks, lock_edges
from tools.tpflcheck.metrics import check_metrics
from tools.tpflcheck.rank import check_rank
from tools.tpflcheck.spmd import check_spmd
from tools.tpflcheck.state import check_state
from tools.tpflcheck.sync import check_sync
from tools.tpflcheck.threads import check_threads
from tools.tpflcheck.trace import check_trace

__all__ = [
    "Violation",
    "Waivers",
    "check_capture",
    "check_donate",
    "check_events",
    "check_guards",
    "check_knobs",
    "check_layers",
    "check_locks",
    "check_metrics",
    "check_rank",
    "check_spmd",
    "check_state",
    "check_sync",
    "check_threads",
    "check_trace",
    "lock_edges",
    "run_all",
    "wire",
]


def run_all(
    repo: "pathlib.Path | None" = None,
) -> "tuple[list[Violation], list[str], list[str], Waivers]":
    """Run every check. Returns (violations-after-waivers, waived
    descriptions, warnings, waivers)."""
    root = repo_root(repo)
    violations: list[Violation] = []
    violations += check_guards(root)
    violations += check_locks(root)
    violations += check_layers(root)
    knob_violations, warnings = check_knobs(root)
    violations += knob_violations
    violations += check_threads(root)
    violations += check_trace(root)
    violations += check_events(root)
    violations += check_metrics(root)
    violations += check_donate(root)
    violations += check_capture(root)
    violations += check_spmd(root)
    violations += check_sync(root)
    violations += wire.violations(root)
    violations += check_state(root)
    violations += check_rank(root)

    waivers = load_waivers(root)
    kept, waived = apply_waivers(violations, waivers)
    # A waiver without a reason is itself a failure — the list is
    # reviewable data, and "because it's waived" is not a review.
    for entry in waivers.unexplained:
        kept.append(
            Violation(
                "waivers", "pyproject.toml", 0,
                f"waiver without a reason: {entry!r} (format: "
                '"<key> = <reason>")',
                f"waivers:{entry}",
            )
        )
    for key in waivers.unused():
        warnings.append(f"stale waiver (matches nothing): {key}")
    return kept, waived, warnings, waivers
