"""Donation lint: a donated buffer is DEAD after dispatch.

``jax.jit(..., donate_argnums=...)`` CONSUMES the listed arguments —
the dispatch aliases (or frees) their buffers, and the caller's Python
binding keeps pointing at the deleted array. Any later use raises
``RuntimeError: Array has been deleted`` at best, and at worst only on
the backend that actually honors the donation — exactly the class of
bug the PR-9 verify drive hit by hand (``run_rounds`` re-stacking
committed inputs) and PR-13's donated-by-default engine path makes
easy to reintroduce.

The lint catches the locally-visible form statically, per function
scope over ``tpfl/``:

1. a callable known to donate: a name bound to
   ``jax.jit(f, donate_argnums=<literal>)`` in the same scope/module,
   or a function decorated with ``@partial(jax.jit,
   donate_argnums=...)`` / ``@jax.jit`` carrying the kwarg;
2. a call of that callable whose donated positions are plain NAME
   arguments;
3. a READ of one of those names on a later line of the same function,
   with no intervening rebind of the name.

Indirect dispatch (``fn(*args)``, attribute-held programs, donation
decided at a different call depth) is out of static reach — the lint
is best-effort on the engine/learner seams, and waivable
(``donate:<file>::<scope>::<name>``). The dynamic complement is the
engine_wire bench tier's donation inspection
(``tpfl.parallel.engine.donation_analysis``), which checks what the
compiled executable really aliases.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Optional

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` (Attribute) or bare ``jit`` imported from jax."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _donated_positions(call: ast.Call) -> Optional[tuple[int, ...]]:
    """Donated argnums when ``call`` is a jax.jit(...) (or
    partial(jax.jit, ...)) carrying a LITERAL donate_argnums."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "partial" and call.args:
        if not _is_jax_jit(call.args[0]):
            return None
    elif not _is_jax_jit(fn):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if not (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                ):
                    return None  # dynamic — out of static reach
                out.append(elt.value)
            return tuple(out)
        return None  # dynamic expression (e.g. the engine's `dn`)
    return None


def _collect_donating(tree: ast.Module) -> dict[str, tuple[int, ...]]:
    """name -> donated positions, for every statically-visible donating
    callable in the module: assignments of jax.jit(...) results and
    decorated function defs. Scope-flattened (the lint only ever
    matches calls by bare name, so a shadowed name just re-binds)."""
    donating: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions(node.value)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donating[t.id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos = _donated_positions(dec)
                    if pos:
                        donating[node.name] = pos
    return donating


def _scope_events(fn: ast.AST, donating: dict[str, tuple[int, ...]]):
    """(donating calls, name loads, name stores) within one function
    scope, excluding nested function/class bodies (their bindings are
    their own scope)."""
    calls: list[tuple[int, str, str]] = []  # (line, donated name, callee)
    loads: list[tuple[int, str]] = []
    stores: list[tuple[int, str]] = []

    def visit(node, top=False):
        if not top and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # nested scope
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            pos = donating.get(node.func.id)
            if pos:
                for i in pos:
                    if i < len(node.args) and isinstance(
                        node.args[i], ast.Name
                    ):
                        calls.append(
                            (node.lineno, node.args[i].id, node.func.id)
                        )
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.append((node.lineno, node.id))
            else:
                stores.append((node.lineno, node.id))
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(fn, top=True)
    return calls, loads, stores


def check_donate(repo: "pathlib.Path | None" = None) -> list[Violation]:
    root = repo_root(repo)
    violations: list[Violation] = []
    for path in py_files(root, "tpfl"):
        try:
            tree = core.parse(path)
        except SyntaxError:
            continue
        donating = _collect_donating(tree)
        if not donating:
            continue
        scopes: list[tuple[str, ast.AST]] = [("<module>", tree)]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node.name, node))
        f = rel(root, path)
        for qual, scope in scopes:
            calls, loads, stores = _scope_events(scope, donating)
            for call_line, name, callee in calls:
                # a Store at or after the call line re-binds the name
                # (covers `p = step(p, x)`, the canonical safe shape)
                rebinds = sorted(
                    ln for ln, n in stores if n == name and ln >= call_line
                )
                for load_line, load_name in loads:
                    if load_name != name or load_line <= call_line:
                        continue
                    if rebinds and rebinds[0] <= load_line:
                        break  # re-bound before (or at) this read
                    violations.append(
                        Violation(
                            "donate", f, load_line,
                            f"`{name}` was donated to `{callee}(...)` on "
                            f"line {call_line} and is read again here — "
                            "a donated buffer is deleted by the "
                            "dispatch; re-bind from the program's "
                            "outputs instead",
                            f"donate:{f}::{qual}::{name}",
                        )
                    )
                    break  # one finding per (call, name)
    return violations
