"""Static lock-order / deadlock lint.

Extracts the lock-acquisition graph from the source of ``tpfl/`` and
fails on cycles — a cycle means two code paths can acquire the same
pair of locks in opposite orders, which deadlocks under the right
interleaving.

What counts as a lock: any attribute / module-level name ending in
``lock`` (the repo's universal naming convention, enforced de facto by
``tpfl.concurrency.make_lock``). Lock IDENTITY is class-qualified
(``Neighbors._lock``), so all instances of a class share a node —
two *different* peer tables locked in opposite orders by two threads
deadlock just as surely as one.

Edges come from two sources:

1. **Nested ``with``** inside one function: holding A while entering
   ``with B:`` adds A→B.
2. **Calls under a held lock**, resolved one level deep with light,
   high-precision type inference: ``self.m()`` resolves within the
   class; ``self.attr.m()`` resolves through ``self.attr = Class(...)``
   assignments in ``__init__``; bare ``f()`` resolves to same-module
   functions. Every lock the callee acquires becomes an edge from each
   held lock. Callbacks and dynamically dispatched sends do NOT
   resolve — that blind spot is exactly what the runtime half covers
   (``Settings.LOCK_TRACING`` + ``tpfl.concurrency.TracedLock``, whose
   graph ``Node.stop`` asserts acyclic).

The edge list doubles as documentation: docs/concurrency.md's
"canonical lock order" section is the topological order of this graph.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field

from tools.tpflcheck import core
from tools.tpflcheck.core import Violation, py_files, rel, repo_root


def _is_lock_name(name: str) -> bool:
    # The repo convention: every lock attribute/name ends in "_lock"
    # (never bare suffix matching — "block"/"clock" are not locks).
    return name.endswith("_lock") or name == "lock"


@dataclass
class _Scope:
    module: str  # repo-relative path
    modbase: str  # module basename, for module-level lock identities
    cls: "str | None" = None
    attr_types: dict[str, str] = field(default_factory=dict)


@dataclass
class Edge:
    src: str
    dst: str
    file: str
    line: int
    via: str  # "" for nested-with, else the resolved callee


class _ModuleIndex:
    """First pass: classes, their lock attrs, attr types, methods."""

    def __init__(self) -> None:
        # class name -> module relpath (assumes unique class names,
        # true in tpfl and asserted loudly below if it breaks)
        self.class_module: dict[str, str] = {}
        # class -> {attr -> ClassName} from `self.attr = Class(...)`
        self.attr_types: dict[str, dict[str, str]] = {}
        # class -> set of lock attr names defined on it
        self.class_locks: dict[str, set[str]] = {}
        # (class|None, func) per module -> FunctionDef for callee summaries
        self.functions: dict[tuple[str, "str | None", str], ast.AST] = {}
        # known class names (for attr-type inference)
        self.known_classes: set[str] = set()

    def build(self, root: pathlib.Path) -> None:
        for path in py_files(root):
            r = rel(root, path)
            tree = core.parse(path)
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    self.known_classes.add(node.name)
                    self.class_module.setdefault(node.name, r)
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            self.functions[(r, node.name, sub.name)] = sub
                        # class-body lock fields (dataclass fields,
                        # class-level locks like _instance_lock)
                        tgt = None
                        if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                            tgt = sub.targets[0]
                        elif isinstance(sub, ast.AnnAssign):
                            tgt = sub.target
                        if isinstance(tgt, ast.Name) and _is_lock_name(tgt.id):
                            self.class_locks.setdefault(node.name, set()).add(
                                tgt.id
                            )
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.functions[(r, None, node.name)] = node
            # self.attr assignments inside methods: lock attrs + types
            for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
                for stmt in ast.walk(cls):
                    tgt = None
                    value = None
                    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                        tgt, value = stmt.targets[0], stmt.value
                    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                        tgt, value = stmt.target, stmt.value
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in ("self", "cls")
                    ):
                        continue
                    if _is_lock_name(tgt.attr):
                        self.class_locks.setdefault(cls.name, set()).add(tgt.attr)
                    if isinstance(value, ast.Call):
                        fn = value.func
                        cname = (
                            fn.id
                            if isinstance(fn, ast.Name)
                            else fn.attr if isinstance(fn, ast.Attribute) else ""
                        )
                        if cname in self.known_classes or cname[:1].isupper():
                            self.attr_types.setdefault(cls.name, {})[
                                tgt.attr
                            ] = cname

    def lock_owner(self, attr: str) -> "str | None":
        """Class that (uniquely) defines lock attribute ``attr``."""
        owners = [c for c, locks in self.class_locks.items() if attr in locks]
        return owners[0] if len(owners) == 1 else None


def _lock_id(expr: ast.expr, scope: _Scope, index: _ModuleIndex) -> "str | None":
    """Identity of a with-item lock expression, or None if not a lock."""
    if isinstance(expr, ast.Name):
        if not _is_lock_name(expr.id):
            return None
        return f"{scope.modbase}.{expr.id}"
    if isinstance(expr, ast.Attribute):
        if not _is_lock_name(expr.attr):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            if scope.cls is not None:
                return f"{scope.cls}.{expr.attr}"
            return f"{scope.modbase}.{expr.attr}"
        # Non-self base: resolve by unique defining class, else by the
        # base's textual name (good enough for module-level singletons).
        owner = index.lock_owner(expr.attr)
        if owner is not None:
            return f"{owner}.{expr.attr}"
        basename = base.id if isinstance(base, ast.Name) else "?"
        return f"{scope.modbase}.{basename}.{expr.attr}"
    return None


def _callee_key(
    call: ast.Call, scope: _Scope, index: _ModuleIndex
) -> "tuple[str, str | None, str] | None":
    """Resolve a call to a (module, class, func) key in the index."""
    fn = call.func
    if isinstance(fn, ast.Name):
        key = (scope.module, None, fn.id)
        return key if key in index.functions else None
    if not isinstance(fn, ast.Attribute):
        return None
    base = fn.value
    if isinstance(base, ast.Name) and base.id in ("self", "cls"):
        if scope.cls is None:
            return None
        key = (scope.module, scope.cls, fn.attr)
        return key if key in index.functions else None
    if (
        isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id in ("self", "cls")
        and scope.cls is not None
    ):
        # self.attr.m() via __init__-inferred attr type
        cname = index.attr_types.get(scope.cls, {}).get(base.attr)
        if cname is None:
            return None
        mod = index.class_module.get(cname)
        if mod is None:
            return None
        key = (mod, cname, fn.attr)
        return key if key in index.functions else None
    return None


def _locks_acquired(
    fn_node: ast.AST, scope: _Scope, index: _ModuleIndex
) -> set[str]:
    """Every lock a function acquires anywhere in its own body
    (one-level callee summary; not transitive)."""
    acquired: set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.With):
            for item in node.items:
                lid = _lock_id(item.context_expr, scope, index)
                if lid is not None:
                    acquired.add(lid)
    return acquired


class _EdgeCollector(ast.NodeVisitor):
    def __init__(
        self, scope: _Scope, index: _ModuleIndex, edges: list[Edge],
        summaries: dict[tuple[str, "str | None", str], set[str]],
    ) -> None:
        self.scope = scope
        self.index = index
        self.edges = edges
        self.summaries = summaries
        self.held: list[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev = self.scope.cls
        self.scope.cls = node.name
        self.generic_visit(node)
        self.scope.cls = prev

    def _enter_fn(self, node: ast.AST) -> None:
        # A with outside a nested function does not protect (or hold
        # across) the function's later execution.
        prev, self.held = self.held, []
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.held = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_fn(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_fn(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_fn(node)

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            lid = _lock_id(item.context_expr, self.scope, self.index)
            if lid is None:
                continue
            for held in self.held:
                if held != lid:
                    self.edges.append(
                        Edge(held, lid, self.scope.module, node.lineno, "")
                    )
            self.held.append(lid)
            acquired.append(lid)
        for stmt in node.body:
            self.visit(stmt)
        for lid in reversed(acquired):
            self.held.remove(lid)

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            key = _callee_key(node, self.scope, self.index)
            if key is not None:
                for lid in sorted(self.summaries.get(key, ())):
                    for held in self.held:
                        if held != lid:
                            self.edges.append(
                                Edge(
                                    held, lid, self.scope.module,
                                    node.lineno,
                                    via=f"{key[1] or key[0]}.{key[2]}",
                                )
                            )
        self.generic_visit(node)


def lock_edges(repo: "pathlib.Path | None" = None) -> list[Edge]:
    """The static lock-acquisition graph of ``tpfl/``."""
    root = repo_root(repo)
    index = _ModuleIndex()
    index.build(root)
    # Callee summaries: locks each indexed function acquires itself.
    summaries: dict[tuple[str, "str | None", str], set[str]] = {}
    for (mod, cls, name), fn_node in index.functions.items():
        scope = _Scope(mod, pathlib.PurePosixPath(mod).stem, cls)
        summaries[(mod, cls, name)] = _locks_acquired(fn_node, scope, index)
    edges: list[Edge] = []
    for path in py_files(root):
        r = rel(root, path)
        tree = core.parse(path)
        scope = _Scope(r, path.stem)
        _EdgeCollector(scope, index, edges, summaries).visit(tree)
    return edges


def check_locks(repo: "pathlib.Path | None" = None) -> list[Violation]:
    edges = lock_edges(repo)
    adj: dict[str, set[str]] = {}
    witness: dict[tuple[str, str], Edge] = {}
    for e in edges:
        adj.setdefault(e.src, set()).add(e.dst)
        witness.setdefault((e.src, e.dst), e)

    WHITE, GREY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    parent: dict[str, str] = {}
    violations: list[Violation] = []

    def dfs(u: str) -> "list[str] | None":
        color[u] = GREY
        for v in sorted(adj.get(u, ())):
            c = color.get(v, WHITE)
            if c == GREY:
                chain = [u]
                while chain[-1] != v:
                    chain.append(parent[chain[-1]])
                chain.reverse()
                chain.append(v)
                return chain
            if c == WHITE:
                parent[v] = u
                found = dfs(v)
                if found is not None:
                    return found
        color[u] = BLACK
        return None

    for node in sorted(adj):
        if color.get(node, WHITE) == WHITE:
            chain = dfs(node)
            if chain is not None:
                steps = []
                for a, b in zip(chain, chain[1:]):
                    e = witness[(a, b)]
                    via = f" via {e.via}" if e.via else ""
                    steps.append(f"{a} -> {b} ({e.file}:{e.line}{via})")
                violations.append(
                    Violation(
                        "locks", "", 0,
                        "lock acquisition cycle (latent deadlock): "
                        + "; ".join(steps),
                        "locks:cycle:" + "->".join(chain),
                    )
                )
                break  # one witness cycle is enough to fail the build
    return violations
