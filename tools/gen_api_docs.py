"""Generate the markdown API reference under ``docs/api/`` from the
``tpfl`` package's docstrings.

The reference ships a sphinx tree with one auto-generated page per
module (``/root/reference/docs/source/modules/*.rst`` + a docs.yml
workflow); this repo's build image has no sphinx, so the same surface
is produced by direct introspection: one ``docs/api/<module>.md`` per
public module — module docstring, public classes (constructor + public
methods with signatures and docstring summaries), public functions —
plus an ``index.md`` grouped by subpackage.

Output is deterministic (sorted walks, no timestamps) so CI can assert
freshness::

    python tools/gen_api_docs.py && git diff --exit-code docs/api

Run with ``JAX_PLATFORMS=cpu`` to avoid grabbing the TPU just to read
docstrings.
"""

from __future__ import annotations

import importlib
import inspect
import pkgutil
import re
import sys
from pathlib import Path
from types import ModuleType

REPO = Path(__file__).resolve().parent.parent
OUT = REPO / "docs" / "api"

# Examples are documented by their own source + docs/README; pb2-style
# generated modules don't exist here.
SKIP_PREFIXES = ("tpfl.examples",)


def _iter_modules() -> list[str]:
    import tpfl

    names = ["tpfl"]
    for info in pkgutil.walk_packages(tpfl.__path__, prefix="tpfl."):
        if info.name.startswith(SKIP_PREFIXES):
            continue
        names.append(info.name)
    return sorted(names)


def _signature(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # Default-value reprs like "<function f at 0x7f...>" embed memory
    # addresses — nondeterministic across runs, which would break the
    # CI freshness check (git diff --exit-code docs/api).
    return re.sub(r" at 0x[0-9a-fA-F]+", "", sig)


def _summary(obj) -> str:
    """First paragraph of the docstring, collapsed to one line."""
    doc = inspect.getdoc(obj)
    if not doc:
        return ""
    para = doc.split("\n\n", 1)[0]
    return " ".join(para.split())


def _full_doc(obj) -> str:
    # flax modules auto-append a constructor signature to the class
    # docstring; its default-value reprs carry memory addresses too.
    return re.sub(r" at 0x[0-9a-fA-F]+", "", inspect.getdoc(obj) or "")


def _public_members(mod: ModuleType):
    """(classes, functions) defined in this module, public-name only.

    ``__all__`` wins when present; otherwise non-underscore names whose
    ``__module__`` matches (so re-exports are documented where they are
    defined, not at every import site).
    """
    allowed = getattr(mod, "__all__", None)
    classes, functions = [], []
    for name in sorted(dir(mod)):
        if allowed is not None:
            if name not in allowed:
                continue
        elif name.startswith("_"):
            continue
        obj = getattr(mod, name)
        if getattr(obj, "__module__", None) != mod.__name__:
            # Re-export: only the package __init__ index mentions it.
            continue
        if inspect.isclass(obj):
            classes.append((name, obj))
        elif inspect.isfunction(obj):
            functions.append((name, obj))
    return classes, functions


def _class_section(name: str, cls: type) -> list[str]:
    lines = [f"### class `{name}{_signature(cls)}`", ""]
    doc = _full_doc(cls)
    if doc:
        lines += [doc, ""]
    methods = []
    for mname in sorted(vars(cls)):
        if mname.startswith("_"):
            continue
        member = inspect.getattr_static(cls, mname)
        if isinstance(member, (staticmethod, classmethod)):
            member = member.__func__
        elif isinstance(member, property):
            summary = _summary(member.fget) if member.fget else ""
            methods.append((f"{mname} (property)", "", summary))
            continue
        if not inspect.isfunction(member):
            continue
        methods.append((mname, _signature(member), _summary(member)))
    if methods:
        lines += ["| method | summary |", "|---|---|"]
        for mname, sig, summary in methods:
            sig_md = f"`{mname}{sig}`" if sig else f"`{mname}`"
            escaped = summary.replace("|", "\\|")
            lines.append(f"| {sig_md} | {escaped} |")
        lines.append("")
    return lines


def _function_section(name: str, fn) -> list[str]:
    lines = [f"### `{name}{_signature(fn)}`", ""]
    doc = _full_doc(fn)
    if doc:
        lines += [doc, ""]
    return lines


def _module_page(modname: str, mod: ModuleType) -> str | None:
    classes, functions = _public_members(mod)
    doc = _full_doc(mod)
    is_pkg = hasattr(mod, "__path__")
    if not (classes or functions) and not doc:
        return None
    lines = [f"# `{modname}`", ""]
    if doc:
        lines += [doc, ""]
    if is_pkg:
        allowed = getattr(mod, "__all__", None)
        exports = [
            n
            for n in sorted(dir(mod))
            if (allowed is None and not n.startswith("_"))
            or (allowed is not None and n in allowed)
        ]
        if exports:
            lines += [
                "**Exports:** " + ", ".join(f"`{n}`" for n in exports),
                "",
            ]
    for name, cls in classes:
        lines += _class_section(name, cls)
    for name, fn in functions:
        lines += _function_section(name, fn)
    return "\n".join(lines).rstrip() + "\n"


def main() -> int:
    sys.path.insert(0, str(REPO))
    OUT.mkdir(parents=True, exist_ok=True)
    pages: dict[str, str] = {}
    for modname in _iter_modules():
        mod = importlib.import_module(modname)
        page = _module_page(modname, mod)
        if page is not None:
            pages[modname] = page

    # Wipe stale pages so renames can't leave orphans behind.
    for old in OUT.glob("*.md"):
        old.unlink()
    for modname, page in pages.items():
        (OUT / f"{modname}.md").write_text(page)

    # Index grouped by top-level subpackage.
    groups: dict[str, list[str]] = {}
    for modname in pages:
        parts = modname.split(".")
        group = parts[1] if len(parts) > 1 else "tpfl"
        groups.setdefault(group, []).append(modname)
    lines = [
        "# API reference",
        "",
        "Generated from docstrings by `tools/gen_api_docs.py` — do not",
        "edit by hand. Regenerate with:",
        "",
        "```bash",
        "JAX_PLATFORMS=cpu python tools/gen_api_docs.py",
        "```",
        "",
    ]
    for group in sorted(groups):
        lines.append(f"## {group}")
        lines.append("")
        for modname in sorted(groups[group]):
            summary = pages[modname].split("\n")
            first = next(
                (ln for ln in summary[2:] if ln.strip()), ""
            )
            first = " ".join(first.split())
            if len(first) > 100:
                first = first[:97] + "..."
            lines.append(f"- [`{modname}`]({modname}.md) — {first}")
        lines.append("")
    (OUT / "index.md").write_text("\n".join(lines).rstrip() + "\n")
    print(f"wrote {len(pages) + 1} pages to {OUT}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
