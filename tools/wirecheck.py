#!/usr/bin/env python
"""Wire-path lint: model payloads must go through the codec registry,
outbound RPCs must go through the retrying send path, and array bytes
must not be copied outside the serialization layer.

Fails (exit 1) when any file under ``tpfl/`` serializes model payloads
with raw ``serialization.encode_pytree`` / ``encode_model_payload`` /
``msgpack.packb`` outside the allowlisted modules. A new code path that
builds weight bytes by hand bypasses the versioned codec envelope
(``tpfl/learning/compression.py``): its payloads would never quantize,
never delta-encode, and — worse — old/new peers could stop agreeing on
the wire format without any test noticing.

Second check (:func:`check_rpc`): no code outside the transport layer
may invoke a gRPC stub/channel or call ``_transport_send`` directly.
Every outbound message must flow through
``ThreadedCommunicationProtocol.send`` — that is where retry/backoff,
the circuit breaker, the fault injector, and the send-health counters
live (``communication/base.py``); a raw ``conn["stubs"]["Send"](...)``
call site would silently skip all four.

Allowlist (each with a reason):

- ``learning/serialization.py``   the v1 envelope implementation
- ``learning/compression.py``     the v2 codec implementation
- ``learning/model.py``           ``encode_parameters`` — the registry
                                  dispatch itself (dense-vs-codec)
- ``communication/message.py``    transport framing (control fields +
                                  already-encoded payload bytes)
- ``communication/grpc_transport.py``  RPC control frames and chunk
                                  frames around already-encoded bytes
- ``management/checkpoint.py``    on-DISK format, deliberately exact
                                  (never rides the wire)

Run: ``python tools/wirecheck.py`` (repo root inferred). Used by the
test suite (tests/test_compression.py) so a violation fails CI.
"""

from __future__ import annotations

import pathlib
import re
import sys

ALLOWED = {
    "tpfl/learning/serialization.py",
    "tpfl/learning/compression.py",
    "tpfl/learning/model.py",
    "tpfl/communication/message.py",
    "tpfl/communication/grpc_transport.py",
    "tpfl/management/checkpoint.py",
}

# Raw serialization entry points a wire path must not touch directly.
PATTERN = re.compile(
    r"(?<![\w.])(?:serialization\.)?(?:encode_pytree|encode_model_payload)\s*\("
    r"|msgpack\.packb\s*\("
)


def check(repo_root: "pathlib.Path | None" = None) -> list[str]:
    """Return a list of 'path:line: offending text' violations."""
    root = repo_root or pathlib.Path(__file__).resolve().parent.parent
    violations: list[str] = []
    for path in sorted((root / "tpfl").rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if rel in ALLOWED:
            continue
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            stripped = line.split("#", 1)[0]
            m = PATTERN.search(stripped)
            if m is None:
                continue
            # compression.encode_model_payload IS the registry path.
            if "compression.encode_model_payload" in stripped:
                continue
            violations.append(f"{rel}:{lineno}: {line.strip()}")
    return violations


# --- copy-discipline lint ------------------------------------------------

# The zero-copy model plane routes every leaf-byte extraction through
# serialization.leaf_bytes (borrowed memoryview, no copy) and every
# decode through zero-copy frombuffer views. A stray `.tobytes()` or a
# `frombuffer(...).copy()` outside the two serialization modules
# reintroduces exactly the per-leaf memcpy the v3 layout removed — and
# does it silently, since the payload still round-trips.
COPIES_ALLOWED = {
    # The serialization layer itself: leaf_bytes' last-resort fallback
    # and the envelope implementations.
    "tpfl/learning/serialization.py",
    "tpfl/learning/compression.py",
}

COPY_PATTERN = re.compile(
    r"\.tobytes\s*\(" r"|frombuffer\s*\([^)]*\)\s*\.copy\s*\("
)


def check_copies(repo_root: "pathlib.Path | None" = None) -> list[str]:
    """Return 'path:line: offending text' for array-byte copies outside
    the serialization layer (route through serialization.leaf_bytes /
    the versioned decode views)."""
    root = repo_root or pathlib.Path(__file__).resolve().parent.parent
    violations: list[str] = []
    for path in sorted((root / "tpfl").rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if rel in COPIES_ALLOWED:
            continue
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            stripped = line.split("#", 1)[0]
            if COPY_PATTERN.search(stripped):
                violations.append(f"{rel}:{lineno}: {line.strip()}")
    return violations


# --- RPC-path lint -------------------------------------------------------

# The only module allowed to touch gRPC stubs/channels.
RPC_ALLOWED = {
    "tpfl/communication/grpc_transport.py",
}

# The only modules allowed to call the raw transport hook: base.py owns
# the retrying dispatch (and the disconnect farewell, deliberately
# fire-once); the transports implement the hook.
SEND_ALLOWED = {
    "tpfl/communication/base.py",
    "tpfl/communication/grpc_transport.py",
    "tpfl/communication/memory.py",
}

# Raw RPC entry points: stub tables, channel construction, stub calls.
RPC_PATTERN = re.compile(
    r"""\[['"]stubs['"]\]"""
    r"|\.unary_unary\s*\("
    r"|\.unary_stream\s*\("
    r"|\.stream_unary\s*\("
    r"|grpc\.(?:insecure|secure)_channel\s*\("
)

# Direct transport-hook calls (not the `def` lines that implement it).
SEND_PATTERN = re.compile(r"\._transport_send(?:_corrupted)?\s*\(")


def check_rpc(repo_root: "pathlib.Path | None" = None) -> list[str]:
    """Return 'path:line: offending text' for outbound RPC call sites
    that bypass the retrying send path."""
    root = repo_root or pathlib.Path(__file__).resolve().parent.parent
    violations: list[str] = []
    for path in sorted((root / "tpfl").rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            stripped = line.split("#", 1)[0]
            if rel not in RPC_ALLOWED and RPC_PATTERN.search(stripped):
                violations.append(f"{rel}:{lineno}: {line.strip()}")
            elif rel not in SEND_ALLOWED and SEND_PATTERN.search(stripped):
                violations.append(f"{rel}:{lineno}: {line.strip()}")
    return violations


def main() -> int:
    rc = 0
    violations = check()
    if violations:
        print(
            "wirecheck FAILED — model payloads serialized outside the "
            "codec registry (route through TpflModel.encode_parameters "
            "or tpfl.learning.compression):",
            file=sys.stderr,
        )
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        rc = 1
    else:
        print(
            "wirecheck OK — all model payload paths go through the codec registry"
        )
    copy_violations = check_copies()
    if copy_violations:
        print(
            "wirecheck FAILED — array bytes copied outside the "
            "serialization layer (route through serialization.leaf_bytes "
            "or the zero-copy decode views):",
            file=sys.stderr,
        )
        for v in copy_violations:
            print(f"  {v}", file=sys.stderr)
        rc = 1
    else:
        print(
            "wirecheck OK — no array-byte copies outside the serialization layer"
        )
    rpc_violations = check_rpc()
    if rpc_violations:
        print(
            "wirecheck FAILED — raw RPC/transport call sites bypass the "
            "retrying send path (route through "
            "ThreadedCommunicationProtocol.send):",
            file=sys.stderr,
        )
        for v in rpc_violations:
            print(f"  {v}", file=sys.stderr)
        rc = 1
    else:
        print(
            "wirecheck OK — all outbound RPC call sites go through the "
            "retrying send path"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
