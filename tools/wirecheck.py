#!/usr/bin/env python
"""Thin shim — the wire lints moved into the tpflcheck suite.

``tools/wirecheck.py`` grew two siblings (copy-discipline, RPC-path)
and then a whole framework: guarded-by race lint, lock-order deadlock
detection, layer/knob/thread lints — ``tools/tpflcheck/``. The three
original checks live in :mod:`tools.tpflcheck.wire` unchanged; this
file keeps the historical entry point (``python tools/wirecheck.py``)
and the ``import wirecheck`` surface the test suite uses.

Prefer ``python -m tools.tpflcheck`` — it runs these three checks AND
the rest of the suite.
"""

from __future__ import annotations

import pathlib
import sys

_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(_ROOT) not in sys.path:
    sys.path.insert(0, str(_ROOT))

from tools.tpflcheck.wire import (  # noqa: E402  (path bootstrap above)
    check,
    check_copies,
    check_rpc,
)

__all__ = ["check", "check_copies", "check_rpc", "main"]


def main() -> int:
    rc = 0
    for label, fn, ok_msg, fail_msg in (
        (
            "wire",
            check,
            "all model payload paths go through the codec registry",
            "model payloads serialized outside the codec registry "
            "(route through TpflModel.encode_parameters or "
            "tpfl.learning.compression)",
        ),
        (
            "copies",
            check_copies,
            "no array-byte copies outside the serialization layer",
            "array bytes copied outside the serialization layer "
            "(route through serialization.leaf_bytes or the zero-copy "
            "decode views)",
        ),
        (
            "rpc",
            check_rpc,
            "all outbound RPC call sites go through the retrying send path",
            "raw RPC/transport call sites bypass the retrying send path "
            "(route through ThreadedCommunicationProtocol.send)",
        ),
    ):
        violations = fn()
        if violations:
            print(f"wirecheck FAILED — {fail_msg}:", file=sys.stderr)
            for v in violations:
                print(f"  {v}", file=sys.stderr)
            rc = 1
        else:
            print(f"wirecheck OK — {ok_msg}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
