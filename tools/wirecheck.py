#!/usr/bin/env python
"""RETIRED — the wire lints live in ``tools.tpflcheck.wire``.

This shim carried the historical ``python tools/wirecheck.py`` entry
point and ``import wirecheck`` surface for two deprecation cycles
after the checks moved into the tpflcheck suite (PR 4). Every in-repo
call site now imports ``tools.tpflcheck.wire`` directly; run
``python -m tools.tpflcheck`` for the full suite.
"""

raise ImportError(
    "tools/wirecheck.py is retired: import tools.tpflcheck.wire "
    "(check / check_copies / check_rpc) or run "
    "`python -m tools.tpflcheck` for the full suite"
)
