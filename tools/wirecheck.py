#!/usr/bin/env python
"""Wire-path lint: model payloads must go through the codec registry.

Fails (exit 1) when any file under ``tpfl/`` serializes model payloads
with raw ``serialization.encode_pytree`` / ``encode_model_payload`` /
``msgpack.packb`` outside the allowlisted modules. A new code path that
builds weight bytes by hand bypasses the versioned codec envelope
(``tpfl/learning/compression.py``): its payloads would never quantize,
never delta-encode, and — worse — old/new peers could stop agreeing on
the wire format without any test noticing.

Allowlist (each with a reason):

- ``learning/serialization.py``   the v1 envelope implementation
- ``learning/compression.py``     the v2 codec implementation
- ``learning/model.py``           ``encode_parameters`` — the registry
                                  dispatch itself (dense-vs-codec)
- ``communication/message.py``    transport framing (control fields +
                                  already-encoded payload bytes)
- ``communication/grpc_transport.py``  RPC control frames and chunk
                                  frames around already-encoded bytes
- ``management/checkpoint.py``    on-DISK format, deliberately exact
                                  (never rides the wire)

Run: ``python tools/wirecheck.py`` (repo root inferred). Used by the
test suite (tests/test_compression.py) so a violation fails CI.
"""

from __future__ import annotations

import pathlib
import re
import sys

ALLOWED = {
    "tpfl/learning/serialization.py",
    "tpfl/learning/compression.py",
    "tpfl/learning/model.py",
    "tpfl/communication/message.py",
    "tpfl/communication/grpc_transport.py",
    "tpfl/management/checkpoint.py",
}

# Raw serialization entry points a wire path must not touch directly.
PATTERN = re.compile(
    r"(?<![\w.])(?:serialization\.)?(?:encode_pytree|encode_model_payload)\s*\("
    r"|msgpack\.packb\s*\("
)


def check(repo_root: "pathlib.Path | None" = None) -> list[str]:
    """Return a list of 'path:line: offending text' violations."""
    root = repo_root or pathlib.Path(__file__).resolve().parent.parent
    violations: list[str] = []
    for path in sorted((root / "tpfl").rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if rel in ALLOWED:
            continue
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            stripped = line.split("#", 1)[0]
            m = PATTERN.search(stripped)
            if m is None:
                continue
            # compression.encode_model_payload IS the registry path.
            if "compression.encode_model_payload" in stripped:
                continue
            violations.append(f"{rel}:{lineno}: {line.strip()}")
    return violations


def main() -> int:
    violations = check()
    if violations:
        print(
            "wirecheck FAILED — model payloads serialized outside the "
            "codec registry (route through TpflModel.encode_parameters "
            "or tpfl.learning.compression):",
            file=sys.stderr,
        )
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("wirecheck OK — all model payload paths go through the codec registry")
    return 0


if __name__ == "__main__":
    sys.exit(main())
