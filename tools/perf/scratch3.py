"""Scratch 3: trustworthy timing (mean->float sync, iter scaling check)
+ lane-padding layout theory tests."""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
from jax import lax

rng = np.random.default_rng(0)
PEAK = 197e12
NB = 12800


def timeit(fn, *args, n=10, tag="", flops=None, bytes_=None):
    """fn must return a SCALAR-reducible array; sync via float(mean)."""
    out = fn(*args)
    float(jnp.asarray(out).mean())  # compile + sync
    for reps in (n, 3 * n):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        float(jnp.asarray(out).mean())
        dt = (time.perf_counter() - t0) / reps
        msg = f"{tag} (reps={reps}): {dt*1e3:.2f} ms"
        if flops:
            msg += f"  ({flops/dt/PEAK*100:.1f}% MFU)"
        if bytes_:
            msg += f"  ({bytes_/dt/1e9:.0f} GB/s)"
        print(msg, flush=True)
    return dt


K = 3
# 1) relu on [NB,32,32,3] (lane-padded 43x?) vs same data as [NB,32,96] (dense lanes)
x_pad = jnp.asarray(rng.normal(size=(NB, 32, 32, 3)), jnp.bfloat16)
x_dense = jnp.asarray(rng.normal(size=(NB, 32, 96)), jnp.bfloat16)
nbytes = NB * 32 * 32 * 3 * 2
timeit(jax.jit(lambda x: jax.nn.relu(x).mean(axis=(1, 2, 3))), x_pad,
       tag="relu NHWC C=3   ", bytes_=2 * nbytes)
timeit(jax.jit(lambda x: jax.nn.relu(x).mean(axis=(1, 2))), x_dense,
       tag="relu dense lanes", bytes_=2 * nbytes)

# 2) conv1 fwd with mean-reduced output (sync honest)
w1 = jnp.asarray(rng.normal(size=(K, K, 3, 32)), jnp.bfloat16)
f1 = NB * 32 * 32 * K * K * 3 * 32 * 2
conv = lambda x, w: lax.conv_general_dilated(
    x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
timeit(jax.jit(lambda x, w: conv(x, w).mean(axis=(1, 2, 3))), x_pad, w1,
       tag="conv1 fwd       ", flops=f1)

# 3) conv2 fwd
x2 = jnp.asarray(rng.normal(size=(NB, 16, 16, 32)), jnp.bfloat16)
w2 = jnp.asarray(rng.normal(size=(K, K, 32, 64)), jnp.bfloat16)
f2 = NB * 16 * 16 * K * K * 32 * 64 * 2
timeit(jax.jit(lambda x, w: conv(x, w).mean(axis=(1, 2, 3))), x2, w2,
       tag="conv2 fwd       ", flops=f2)

# 4) batched GEMM conv2-shape with honest sync
N, M2, P2, C2 = 100, 32768, 288, 64
pa = jnp.asarray(rng.normal(size=(N, M2, P2)), jnp.bfloat16)
wb = jnp.asarray(rng.normal(size=(N, P2, C2)), jnp.bfloat16)
fb = 2 * N * M2 * P2 * C2
timeit(jax.jit(lambda a, b: lax.dot_general(
    a, b, (((2,), (1,)), ((0,), (0,)))).mean(axis=(1, 2))), pa, wb,
    tag="batched GEMM    ", flops=fb)

# 5) single big GEMM [N*M2, P2] @ [P2, 128] — MXU sanity ceiling
pf = pa.reshape(N * M2, P2)
wfat = jnp.asarray(rng.normal(size=(P2, 128)), jnp.bfloat16)
timeit(jax.jit(lambda a, b: (a @ b).mean(axis=1)), pf, wfat,
       tag="GEMM K288 N128  ", flops=2 * N * M2 * P2 * 128)

# 6) big square-ish GEMM: true MXU peak check
A = jnp.asarray(rng.normal(size=(8192, 4096)), jnp.bfloat16)
Bm = jnp.asarray(rng.normal(size=(4096, 8192)), jnp.bfloat16)
timeit(jax.jit(lambda a, b: (a @ b).mean(axis=1)), A, Bm,
       tag="GEMM 8k/4k/8k   ", flops=2 * 8192 * 4096 * 8192)
