"""Scratch 8: end-to-end vmapped train-step variants.

Baseline (XLA grouped conv fwd+bwd): 22.03 ms / 10.8% MFU (measured).
B) custom-VJP conv: XLA conv fwd, GEMM dW, GEMM+col2im dx.
C) im2col fwd, plain autodiff.
"""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

rng = np.random.default_rng(0)
PEAK = 197e12
N, BS = 100, 128
R = 20

DN = ("NHWC", "HWIO", "NHWC")


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT baseline: {BASE*1e3:.1f} ms", flush=True)


# --- custom-VJP conv ---
@jax.custom_vjp
def node_conv(x, w):
    return lax.conv_general_dilated(x, w, (1, 1), "SAME", dimension_numbers=DN)


def _nc_fwd(x, w):
    return node_conv(x, w), (x, w)


def _nc_bwd(res, g):
    x, w = res
    B, H, W, Cin = x.shape
    kh, kw, _, Cout = w.shape
    M = B * H * W
    P = Cin * kh * kw
    g = g.astype(x.dtype)
    # dW = patches(x)^T @ g   [P, M] x [M, Cout] — K is huge, MXU-friendly
    p = lax.conv_general_dilated_patches(x, (kh, kw), (1, 1), "SAME", dimension_numbers=DN)
    pm = p.reshape(M, P)
    gm = g.reshape(M, Cout)
    dwm = lax.dot_general(pm, gm, (((0,), (0,)), ((), ())))  # [P, Cout]
    dw = dwm.reshape(Cin, kh, kw, Cout).transpose(1, 2, 0, 3).astype(w.dtype)
    # dx: dpatches = g @ wm^T  [M, Cout] x [Cout, P], then col2im shifts
    wm = w.transpose(2, 0, 1, 3).reshape(P, Cout)
    dp = lax.dot_general(gm, wm, (((1,), (1,)), ((), ())))  # [M, P]
    dp = dp.reshape(B, H, W, Cin, kh, kw)
    ph, pw = (kh - 1) // 2, (kw - 1) // 2
    dx = jnp.zeros_like(x)
    for di in range(kh):
        for dj in range(kw):
            piece = dp[:, :, :, :, di, dj]
            padded = jnp.pad(
                piece, ((0, 0), (di, kh - 1 - di), (dj, kw - 1 - dj), (0, 0))
            )
            dx = dx + padded[:, ph:ph + H, pw:pw + W, :]
    return dx, dw


node_conv.defvjp(_nc_fwd, _nc_bwd)


def conv_plain(x, w):
    return lax.conv_general_dilated(x, w, (1, 1), "SAME", dimension_numbers=DN)


def conv_im2col(x, w):
    kh, kw, cin, cout = w.shape
    p = lax.conv_general_dilated_patches(x, (kh, kw), (1, 1), "SAME", dimension_numbers=DN)
    wm = w.transpose(2, 0, 1, 3).reshape(cin * kh * kw, cout)
    return lax.dot_general(p, wm, (((3,), (0,)), ((), ())))


def make_step(conv):
    pool = lambda y: lax.reduce_window(
        y, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")

    def net(params, x):
        y = conv(x, params["w1"])
        y = pool(jax.nn.relu(y + params["b1"]))
        y = conv(y, params["w2"])
        y = pool(jax.nn.relu(y + params["b2"]))
        y = y.reshape(y.shape[0], -1)
        y = jax.nn.relu(y @ params["wd"] + params["bd"])
        return (y @ params["wo"] + params["bo"]).astype(jnp.float32)

    opt = optax.sgd(0.1, momentum=0.9)

    def one(pp, oo, xx, yy):
        def loss_of(q):
            logits = net(q, xx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

        loss, grads = jax.value_and_grad(loss_of)(pp)
        up, oo = opt.update(grads, oo, pp)
        return optax.apply_updates(pp, up), oo

    def step(t, i):
        p, o = t
        return jax.vmap(one)(p, o, x_dev, y_dev)

    return step, opt


def init_params():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 6)
    p1 = {
        "w1": jax.random.normal(ks[0], (3, 3, 3, 32), jnp.bfloat16) * 0.1,
        "b1": jnp.zeros((32,), jnp.bfloat16),
        "w2": jax.random.normal(ks[1], (3, 3, 32, 64), jnp.bfloat16) * 0.05,
        "b2": jnp.zeros((64,), jnp.bfloat16),
        "wd": jax.random.normal(ks[2], (4096, 128), jnp.bfloat16) * 0.02,
        "bd": jnp.zeros((128,), jnp.bfloat16),
        "wo": jax.random.normal(ks[3], (128, 10), jnp.bfloat16) * 0.1,
        "bo": jnp.zeros((10,), jnp.bfloat16),
    }
    return jax.tree_util.tree_map(
        lambda q: jnp.broadcast_to(q[None], (N, *q.shape)) + 0, p1
    )


x_dev = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 3)), jnp.bfloat16)
y_dev = jnp.asarray(rng.integers(0, 10, (N, BS)), jnp.int32)

fs = (32 * 32 * 9 * 3 * 32 + 16 * 16 * 9 * 32 * 64 + 4096 * 128 + 128 * 10) * 2
f_step = 3 * fs * N * BS


def measure(tag, conv):
    step, opt = make_step(conv)
    params = init_params()
    opt_state = jax.vmap(opt.init)(params)

    @jax.jit
    def run(t):
        return lax.fori_loop(0, R, lambda i, t: step(t, i), t)

    out = run((params, opt_state))
    float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = run((params, opt_state))
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    per = (best - BASE) / R
    print(f"{tag}: {per*1e3:.2f} ms  ({f_step/per/PEAK*100:.1f}% MFU)", flush=True)


# numeric check first (tiny, grads close to plain autodiff)
xt = jnp.asarray(rng.normal(size=(2, 8, 8, 3)), jnp.float32)
wt = jnp.asarray(rng.normal(size=(3, 3, 3, 5)), jnp.float32)
g_custom = jax.grad(lambda w: jnp.sum(node_conv(xt, w) ** 2))(wt)
g_ref = jax.grad(lambda w: jnp.sum(conv_plain(xt, w) ** 2))(wt)
gx_custom = jax.grad(lambda x: jnp.sum(node_conv(x, wt) ** 2))(xt)
gx_ref = jax.grad(lambda x: jnp.sum(conv_plain(x, wt) ** 2))(xt)
print("dW err:", float(jnp.abs(g_custom - g_ref).max()),
      "dx err:", float(jnp.abs(gx_custom - gx_ref).max()), flush=True)

measure("B custom-vjp step", node_conv)
measure("C im2col fwd step", conv_im2col)
