"""Scratch: verify conv_general_dilated_patches channel ordering vs nn.Conv,
and micro-bench vmapped grouped-conv vs im2col batched-GEMM on the chip."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

rng = np.random.default_rng(0)

# --- ordering check (f32, CPU-precision enough on TPU for structure) ---
B, H, W, Cin, Cout, K = 2, 8, 8, 3, 5, 3
x = jnp.asarray(rng.normal(size=(B, H, W, Cin)), jnp.float32)
w = jnp.asarray(rng.normal(size=(K, K, Cin, Cout)), jnp.float32)

ref = lax.conv_general_dilated(
    x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
)

patches = lax.conv_general_dilated_patches(
    x, (K, K), (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
)
print("patches shape:", patches.shape)  # [B, H, W, ?]

# hypothesis A: feature dim ordered (Cin, K, K) i.e. channel-major
wa = jnp.transpose(w, (2, 0, 1, 3)).reshape(Cin * K * K, Cout)
outa = patches @ wa
# hypothesis B: ordered (K, K, Cin)
wb = w.reshape(K * K * Cin, Cout)
outb = patches @ wb
print("A err:", float(jnp.abs(outa - ref).max()), "B err:", float(jnp.abs(outb - ref).max()))

# --- micro-bench: N-node vmapped conv, grouped vs im2col ---
N, B, H, W, Cin, Cout, K = 100, 128, 32, 32, 3, 32, 3
C2 = 64
xs = jnp.asarray(rng.normal(size=(N, B, H, W, Cin)), jnp.bfloat16)
w1 = jnp.asarray(rng.normal(size=(N, K, K, Cin, Cout)), jnp.bfloat16)
w2 = jnp.asarray(rng.normal(size=(N, K, K, Cout, C2)), jnp.bfloat16)


def conv_xla(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


def conv_im2col(x, w):
    kh, kw, cin, cout = w.shape
    p = lax.conv_general_dilated_patches(
        x, (kh, kw), (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    wm = jnp.transpose(w, (2, 0, 1, 3)).reshape(cin * kh * kw, cout)
    return jax.lax.dot_general(p, wm, (((3,), (0,)), ((), ())))


def net(conv, x, wa, wb):
    y = conv(x, wa)
    y = jax.nn.relu(y)
    y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    y = conv(y, wb)
    return y


def bench(conv, tag):
    def loss(wa, wb):
        out = jax.vmap(lambda x, a, b: net(conv, x, a, b))(xs, wa, wb)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1)))
    t0 = time.perf_counter()
    out = g(w1, w2)
    jax.block_until_ready(out)
    print(tag, "compile+1st:", round(time.perf_counter() - t0, 2))
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        out = g(w1, w2)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    # fwd flops of the two convs
    f = N * B * (H * W * K * K * Cin * Cout + (H // 2) * (W // 2) * K * K * Cout * C2) * 2
    print(tag, f"per-iter {dt*1e3:.1f} ms, fwd+bwd~3x fwd MFU ≈ {3*f/dt/197e12*100:.1f}%")


print("devices:", jax.devices())
bench(conv_xla, "xla-conv  ")
bench(conv_im2col, "im2col    ")
