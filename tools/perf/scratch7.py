"""Scratch 7: breakdown of the vmapped round + candidate GEMM shapes."""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from tpfl.models import CNN
from tpfl.parallel.federation import _diffuse

rng = np.random.default_rng(0)
PEAK = 197e12
N, BS = 100, 128


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT baseline: {BASE*1e3:.1f} ms", flush=True)


def devtime(fn, tree0, tag="", flops=None, R=20):
    """fn: tree -> tree (same structure); serialized fori on device."""

    @jax.jit
    def run(t):
        return lax.fori_loop(0, R, lambda i, t: fn(t, i), t)

    out = run(tree0)
    float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = run(tree0)
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    per = (best - BASE) / R
    msg = f"{tag}: {per*1e3:.2f} ms"
    if flops:
        msg += f"  ({flops/per/PEAK*100:.1f}% MFU)"
    print(msg, flush=True)
    return per


module = CNN(out_channels=10)
variables = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
p1 = variables["params"]
params = jax.tree_util.tree_map(lambda p: jnp.broadcast_to(p[None], (N, *p.shape)) + 0, p1)
x = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 3)), jnp.bfloat16)
y = jnp.asarray(rng.integers(0, 10, (N, BS)), jnp.int32)

fs = (32 * 32 * 9 * 3 * 32 + 16 * 16 * 9 * 32 * 64 + 4096 * 128 + 128 * 10) * 2
f_batch = fs * N * BS

# 1) vmapped fwd one batch
def fwd(t, i):
    p, acc = t
    logits = jax.vmap(lambda pp, xx: module.apply({"params": pp}, xx, train=False))(p, x * (1 + 1e-6 * i))
    return p, acc + logits.mean()

devtime(fwd, (params, jnp.float32(0)), tag="vmapped fwd 1batch   ", flops=f_batch)

# 2) vmapped fwd+bwd+sgd one step
opt = optax.sgd(0.1, momentum=0.9)
opt_state = jax.vmap(opt.init)(params)

def step(t, i):
    p, o = t

    def one(pp, oo, xx, yy):
        def loss_of(q):
            logits = module.apply({"params": q}, xx, train=False)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

        loss, g = jax.value_and_grad(loss_of)(pp)
        up, oo = opt.update(g, oo, pp)
        return optax.apply_updates(pp, up), oo

    p, o = jax.vmap(one)(p, o, x, y)
    return p, o

devtime(step, (params, opt_state), tag="vmapped train step   ", flops=3 * f_batch)

# 3) aggregation alone
w = jnp.ones((N,), jnp.float32)

def agg(t, i):
    p = t
    return _diffuse(jax.tree_util.tree_map(lambda q: q * (1 + 1e-6 * i), p), w)

devtime(agg, params, tag="fedavg diffuse       ")

# 4) conv2 backward GEMM shapes (batched)
M2, P2, C2 = BS * 16 * 16, 9 * 32, 64
A_dx = jnp.asarray(rng.normal(size=(N, M2, C2)), jnp.bfloat16)   # dout
B_dx = jnp.asarray(rng.normal(size=(N, C2, P2)), jnp.bfloat16)   # w^T
fb = 2 * N * M2 * P2 * C2

def g_dx(t, i):
    a, b, acc = t
    out = lax.dot_general(a * (1 + 1e-6 * i), b, (((2,), (1,)), ((0,), (0,))))
    return a, b, acc + out.mean()

devtime(g_dx, (A_dx, B_dx, jnp.float32(0)), tag="GEMM dx  [M,64]x[64,288] ", flops=fb)

A_dw = jnp.asarray(rng.normal(size=(N, P2, M2)), jnp.bfloat16)   # patches^T
B_dw = jnp.asarray(rng.normal(size=(N, M2, C2)), jnp.bfloat16)   # dout
devtime(g_dx, (A_dw, B_dw, jnp.float32(0)), tag="GEMM dW  [288,M]x[M,64]  ", flops=fb)

# 5) conv1 s2d GEMM: [N, B*256, 48] @ [N, 48, 128] (4 output pixels x 32ch)
M1s, P1s, C1s = BS * 16 * 16, 48, 128
A_s2d = jnp.asarray(rng.normal(size=(N, M1s, P1s)), jnp.bfloat16)
B_s2d = jnp.asarray(rng.normal(size=(N, P1s, C1s)), jnp.bfloat16)
f_s2d_useful = 2 * N * BS * 32 * 32 * 27 * 32  # useful conv1 flops
devtime(g_dx, (A_s2d, B_s2d, jnp.float32(0)), tag="GEMM s2d [M,48]x[48,128] ", flops=f_s2d_useful)

# 6) patches extraction cost, conv2 (node-folded layout)
x2 = jnp.asarray(rng.normal(size=(N * BS, 16, 16, 32)), jnp.bfloat16)

def patches(t, i):
    xx, acc = t
    p = lax.conv_general_dilated_patches(
        xx * (1 + 1e-6 * i), (3, 3), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return xx, acc + p.mean()

devtime(patches, (x2, jnp.float32(0)), tag="patches conv2        ")
