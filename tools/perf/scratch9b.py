"""Scratch 9: decompose the vmapped bwd cost by grad subset.
dense-only -> +conv2 dW -> full (adds conv2-dx + conv1-dW)."""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

rng = np.random.default_rng(0)
PEAK = 197e12
N, BS = 100, 128
R = 20
DN = ("NHWC", "HWIO", "NHWC")


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT baseline: {BASE*1e3:.1f} ms", flush=True)


def conv_plain(x, w):
    return lax.conv_general_dilated(x, w, (1, 1), "SAME", dimension_numbers=DN)


def init_params():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 6)
    p1 = {
        "w1": jax.random.normal(ks[0], (3, 3, 3, 32), jnp.bfloat16) * 0.1,
        "b1": jnp.zeros((32,), jnp.bfloat16),
        "w2": jax.random.normal(ks[1], (3, 3, 32, 64), jnp.bfloat16) * 0.05,
        "b2": jnp.zeros((64,), jnp.bfloat16),
        "wd": jax.random.normal(ks[2], (4096, 128), jnp.bfloat16) * 0.02,
        "bd": jnp.zeros((128,), jnp.bfloat16),
        "wo": jax.random.normal(ks[3], (128, 10), jnp.bfloat16) * 0.1,
        "bo": jnp.zeros((10,), jnp.bfloat16),
    }
    return jax.tree_util.tree_map(
        lambda q: jnp.broadcast_to(q[None], (N, *q.shape)) + 0, p1
    )


x_dev = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 3)), jnp.bfloat16)
y_dev = jnp.asarray(rng.integers(0, 10, (N, BS)), jnp.int32)


def make_subset_step(grad_keys):
    conv = conv_plain
    pool = lambda y: lax.reduce_window(
        y, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")

    def net(params, x):
        y = conv(x, params["w1"])
        y = pool(jax.nn.relu(y + params["b1"]))
        y = conv(y, params["w2"])
        y = pool(jax.nn.relu(y + params["b2"]))
        y = y.reshape(y.shape[0], -1)
        y = jax.nn.relu(y @ params["wd"] + params["bd"])
        return (y @ params["wo"] + params["bo"]).astype(jnp.float32)

    opt = optax.sgd(0.1, momentum=0.9)

    def one(pp, oo, xx, yy):
        live = {k: pp[k] for k in grad_keys}
        frozen = {k: jax.lax.stop_gradient(pp[k]) for k in pp if k not in grad_keys}

        def loss_of(q):
            logits = net({**frozen, **q}, xx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

        loss, grads = jax.value_and_grad(loss_of)(live)
        full_grads = {k: grads.get(k, jnp.zeros_like(pp[k])) for k in pp}
        up, oo = opt.update(full_grads, oo, pp)
        return optax.apply_updates(pp, up), oo

    def step(t, i):
        p, o = t
        return jax.vmap(one)(p, o, x_dev, y_dev)

    return step, opt


def measure(tag, grad_keys):
    step, opt = make_subset_step(grad_keys)
    params = init_params()
    opt_state = jax.vmap(opt.init)(params)

    @jax.jit
    def run(t):
        return lax.fori_loop(0, R, lambda i, t: step(t, i), t)

    out = run((params, opt_state))
    float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = run((params, opt_state))
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    per = (best - BASE) / R
    print(f"{tag}: {per*1e3:.2f} ms", flush=True)


measure("+conv2 dx (b1)   ", ["b1", "w2", "b2", "wd", "bd", "wo", "bo"])
measure("full grads       ", ["w1", "b1", "w2", "b2", "wd", "bd", "wo", "bo"])
