"""Scratch 6: device-side timing of the REAL VmapFederation round and
its pieces. One TPU process at a time!"""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
from jax import lax

from tpfl.models import CNN
from tpfl.parallel import VmapFederation

rng = np.random.default_rng(0)
PEAK = 197e12
N, NBATCH, BS = 100, 4, 128


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT baseline: {BASE*1e3:.1f} ms", flush=True)

fed = VmapFederation(CNN(out_channels=10), n_nodes=N, learning_rate=0.1, seed=0)
params = fed.init_params((32, 32, 3))
xs = jnp.asarray(rng.normal(size=(N, NBATCH, BS, 32, 32, 3)), jnp.bfloat16)
ys = jnp.asarray(rng.integers(0, 10, (N, NBATCH, BS)), jnp.int32)
w = jnp.ones((N,), jnp.float32)

round_fn = fed._build_round()

# flops: per-sample fwd model flops (conv1+conv2+dense1+dense2) x3 for bwd
fs = (32 * 32 * 9 * 3 * 32 + 16 * 16 * 9 * 32 * 64 + 4096 * 128 + 128 * 10) * 2
round_flops = 3 * fs * N * NBATCH * BS
print(f"analytic round flops: {round_flops/1e12:.3f} TF", flush=True)

R = 10


@jax.jit
def many_rounds(p, xs, ys, w):
    def body(i, p):
        p2, losses = round_fn(p, xs, ys, w, 1)
        return p2

    return lax.fori_loop(0, R, body, p)


out = many_rounds(params, xs, ys, w)
float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])  # compile+sync
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    out = many_rounds(params, xs, ys, w)
    float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    best = min(best, time.perf_counter() - t0)
per_round = (best - BASE) / R
print(
    f"device round: {per_round*1e3:.1f} ms  "
    f"({round_flops/per_round/PEAK*100:.1f}% MFU)  "
    f"[{N*NBATCH*BS/per_round:.0f} samples/s]",
    flush=True,
)

# host-loop comparison (bench.py's current method): 10 dispatches + 1 sync
compiled = round_fn.lower(params, xs, ys, w, 1).compile()
p2, losses = compiled(params, xs, ys, w)
float(np.asarray(losses).mean())
t0 = time.perf_counter()
for _ in range(10):
    p2, losses = compiled(p2, xs, ys, w)
float(np.asarray(losses).mean())
host_per_round = (time.perf_counter() - t0) / 10
print(
    f"host-loop round: {host_per_round*1e3:.1f} ms  "
    f"({round_flops/host_per_round/PEAK*100:.1f}% MFU)",
    flush=True,
)
