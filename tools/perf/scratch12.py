"""Scratch 12: custom VJP with fwd-style XLA bwd convs + shared-weight
parity check. 3 compiles max."""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

rng = np.random.default_rng(0)
PEAK = 197e12
N, BS = 100, 128
R = 20
DN = ("NHWC", "HWIO", "NHWC")


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT baseline: {BASE*1e3:.1f} ms", flush=True)


@jax.custom_vjp
def conv_fb(x, w):
    return lax.conv_general_dilated(x, w, (1, 1), "SAME", dimension_numbers=DN)


def _fb_fwd(x, w):
    return conv_fb(x, w), (x, w)


def _fb_bwd(res, g):
    x, w = res
    g = g.astype(x.dtype)
    k = w.shape[0]
    r = k // 2
    # dx: plain SAME conv of g with the flipped, io-swapped kernel.
    w_flip = jnp.flip(w, (0, 1)).swapaxes(2, 3)  # [k,k,Cout,Cin]
    dx = lax.conv_general_dilated(
        g, w_flip, (1, 1), "SAME", dimension_numbers=DN
    )
    # dW: conv with Cin as batch, B as contraction feature, g as kernel.
    dw = lax.conv_general_dilated(
        x, g, (1, 1), [(r, r), (r, r)],
        dimension_numbers=("CHWN", "IHWO", "HWNC"),
    ).astype(w.dtype)
    return dx, dw


conv_fb.defvjp(_fb_fwd, _fb_bwd)

# correctness spot-check on-chip (f32)
xt = jnp.asarray(rng.normal(size=(2, 8, 8, 3)), jnp.float32)
wt = jnp.asarray(rng.normal(size=(3, 3, 3, 5)), jnp.float32)
ref = lambda x, w: lax.conv_general_dilated(x, w, (1, 1), "SAME", dimension_numbers=DN)
ga = jax.grad(lambda w: jnp.sum(conv_fb(xt, w) ** 2))(wt)
gb = jax.grad(lambda w: jnp.sum(ref(xt, w) ** 2))(wt)
gxa = jax.grad(lambda x: jnp.sum(conv_fb(x, wt) ** 2))(xt)
gxb = jax.grad(lambda x: jnp.sum(ref(x, wt) ** 2))(xt)
print("dW err:", float(jnp.abs(ga - gb).max()), "dx err:",
      float(jnp.abs(gxa - gxb).max()), flush=True)

x_dev = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 3)), jnp.bfloat16)
y_dev = jnp.asarray(rng.integers(0, 10, (N, BS)), jnp.int32)
fs = (32 * 32 * 9 * 3 * 32 + 16 * 16 * 9 * 32 * 64 + 4096 * 128 + 128 * 10) * 2
f_step = 3 * fs * N * BS


def measure(tag, conv, shared=False):
    pool = lambda y: lax.reduce_window(
        y, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")

    def net(params, x):
        y = conv(x, params["w1"])
        y = pool(jax.nn.relu(y + params["b1"]))
        y = conv(y, params["w2"])
        y = pool(jax.nn.relu(y + params["b2"]))
        y = y.reshape(y.shape[0], -1)
        y = jax.nn.relu(y @ params["wd"] + params["bd"])
        return (y @ params["wo"] + params["bo"]).astype(jnp.float32)

    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 6)
    p1 = {
        "w1": jax.random.normal(ks[0], (3, 3, 3, 32), jnp.bfloat16) * 0.1,
        "b1": jnp.zeros((32,), jnp.bfloat16),
        "w2": jax.random.normal(ks[1], (3, 3, 32, 64), jnp.bfloat16) * 0.05,
        "b2": jnp.zeros((64,), jnp.bfloat16),
        "wd": jax.random.normal(ks[2], (4096, 128), jnp.bfloat16) * 0.02,
        "bd": jnp.zeros((128,), jnp.bfloat16),
        "wo": jax.random.normal(ks[3], (128, 10), jnp.bfloat16) * 0.1,
        "bo": jnp.zeros((10,), jnp.bfloat16),
    }
    opt = optax.sgd(0.1, momentum=0.9)

    def one(pp, oo, xx, yy):
        def loss_of(q):
            logits = net(q, xx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

        loss, grads = jax.value_and_grad(loss_of)(pp)
        up, oo = opt.update(grads, oo, pp)
        return optax.apply_updates(pp, up), oo

    if shared:
        params = p1
        opt_state = opt.init(params)
        xbig = x_dev.reshape(N * BS, 32, 32, 3)
        ybig = y_dev.reshape(N * BS)

        def step(t, i):
            p, o = t
            return one(p, o, xbig, ybig)
    else:
        params = jax.tree_util.tree_map(
            lambda q: jnp.broadcast_to(q[None], (N, *q.shape)) + 0, p1)
        opt_state = jax.vmap(opt.init)(params)

        def step(t, i):
            p, o = t
            return jax.vmap(one)(p, o, x_dev, y_dev)

    @jax.jit
    def run(t):
        return lax.fori_loop(0, R, lambda i, t: step(t, i), t)

    out = run((params, opt_state))
    float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = run((params, opt_state))
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    per = (best - BASE) / R
    print(f"{tag}: {per*1e3:.2f} ms  ({f_step/per/PEAK*100:.1f}% MFU)", flush=True)


measure("fwd-style-bwd vjp step", conv_fb)
measure("shared-weight step    ", lambda x, w: lax.conv_general_dilated(
    x, w, (1, 1), "SAME", dimension_numbers=DN), shared=True)
