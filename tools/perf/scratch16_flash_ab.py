"""A/B the tpfl flash kernel against jax's reference TPU flash kernel
and the XLA blockwise path — fwd-only and fwd+bwd — with the bench's
device-side fori_loop timing (RTT-subtracted, best of 3).

Receipts for the r5 attention-tier investigation: r4's host-loop
numbers (496k/374k toks/s) were irreproducible; honest timing measured
the r4 kernel at 42k toks/s @8k — SLOWER than XLA blockwise (67k).
Prime suspect: every kernel matmul upcast operands to f32 (fraction of
bf16 MXU rate). This harness measures the fix and the remaining gap to
the reference kernel.

Run on the real chip: python tools/perf/scratch16_flash_ab.py
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpfl.parallel.flash_kernel import flash_attention
from tpfl.parallel.ring_attention import blockwise_attention

try:
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention as jax_flash,
    )

    HAVE_REF = True
except Exception:
    HAVE_REF = False


def _sync(out):
    # block_until_ready does not reliably block under this plugin
    # (docs/perf_cnn.md): force a device->host copy of one leaf.
    leaf = jax.tree_util.tree_leaves(out)[-1]
    float(np.asarray(leaf).ravel()[0])


def best_of(fn, *args, n=3):
    out = fn(*args)
    _sync(out)
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


@jax.jit
def empty_call(x):
    return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))


def timed_loop(step, carry, n_iters, rtt):
    @jax.jit
    def run(c):
        out = lax.fori_loop(0, n_iters, lambda i, cc: step(cc), c)
        # Scalar out: syncing on an array carry copies it to host over
        # the tunnel (tens of MB — dwarfs the device time measured).
        return sum(
            x.ravel()[0].astype(jnp.float32)
            for x in jax.tree_util.tree_leaves(out)
        )

    total, out = best_of(run, carry)
    return max(total - rtt, 1e-9) / n_iters


def main():
    rtt, _ = best_of(empty_call, jnp.float32(1))
    print(f"rtt={rtt * 1e3:.1f}ms")
    B, H, D = 1, 8, 128
    rng = np.random.default_rng(0)
    for S, iters in ((8192, 96), (32768, 16)):
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16)
            for _ in range(3)
        )
        # jax reference kernel wants [B, H, S, D]
        qh, kh, vh = (jnp.moveaxis(x, 2, 1) for x in (q, k, v))

        variants = {
            "tpfl_flash": lambda q=q, k=k, v=v: flash_attention(
                q, k, v, causal=True
            ),
            "xla_blockwise": lambda q=q, k=k, v=v: blockwise_attention(
                q, k, v, causal=True
            ),
        }
        if HAVE_REF:

            def ref(qh=qh, kh=kh, vh=vh):
                return jax_flash(qh, kh, vh, causal=True)

            variants["jax_ref_flash"] = ref

        for name, fn in variants.items():
            # fwd only
            try:
                arg0 = q if name != "jax_ref_flash" else qh

                def fwd_step(c, fn=fn, name=name):
                    o = fn()
                    return c + o.astype(jnp.float32).sum()

                per = timed_loop(
                    lambda c, fn=fn: c + fn().astype(jnp.float32).sum(),
                    jnp.float32(0),
                    iters,
                    rtt,
                )
                print(
                    f"S={S} {name:14s} fwd      {B * S / per / 1e3:9.1f}k toks/s"
                )
            except Exception as e:
                print(f"S={S} {name:14s} fwd      ERROR {str(e)[:100]}")
            # fwd+bwd
            try:
                if name == "jax_ref_flash":

                    def loss(qx, kx, vx):
                        return jnp.sum(
                            jax_flash(qx, kx, vx, causal=True).astype(
                                jnp.float32
                            )
                            ** 2
                        )

                    def step(c):
                        qx, kx, vx = c
                        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(
                            qx, kx, vx
                        )
                        return (
                            qx - 1e-6 * dq.astype(qx.dtype),
                            kx - 1e-6 * dk.astype(kx.dtype),
                            vx - 1e-6 * dv.astype(vx.dtype),
                        )

                    carry = (qh, kh, vh)
                else:
                    f = (
                        flash_attention
                        if name == "tpfl_flash"
                        else lambda a, b, c_, causal: blockwise_attention(
                            a, b, c_, causal=causal
                        )
                    )

                    def loss(qx, kx, vx, f=f):
                        return jnp.sum(
                            f(qx, kx, vx, causal=True).astype(jnp.float32) ** 2
                        )

                    def step(c, loss=loss):
                        qx, kx, vx = c
                        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(
                            qx, kx, vx
                        )
                        return (
                            qx - 1e-6 * dq.astype(qx.dtype),
                            kx - 1e-6 * dk.astype(kx.dtype),
                            vx - 1e-6 * dv.astype(vx.dtype),
                        )

                    carry = (q, k, v)
                per = timed_loop(step, carry, iters, rtt)
                print(
                    f"S={S} {name:14s} fwd+bwd  {B * S / per / 1e3:9.1f}k toks/s"
                )
            except Exception as e:
                print(f"S={S} {name:14s} fwd+bwd  ERROR {str(e)[:100]}")


if __name__ == "__main__":
    main()
