import os, time
import jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp, numpy as np
from tpfl.parallel.ring_attention import blockwise_attention

rng = np.random.default_rng(0)
B, H, D = 1, 8, 128
for S in (8192, 32768):
    q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16) for _ in range(3))
    def loss(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    t0 = time.perf_counter()
    out = g(q, k, v)
    float(jnp.asarray(out[0]).ravel()[0])
    print(f"S={S}: compile+1st {time.perf_counter()-t0:.1f}s", flush=True)
    n = 5
    t0 = time.perf_counter()
    for _ in range(n):
        out = g(q, k, v)
    float(jnp.asarray(out[0]).ravel()[0])
    print(f"S={S}: {B*S*n/(time.perf_counter()-t0):.0f} toks/s fwd+bwd", flush=True)
