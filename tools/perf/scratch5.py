"""Scratch 5: R=100 device reps, subtract empty-call RTT baseline."""
import os, time
import jax
jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np
from jax import lax

rng = np.random.default_rng(0)
PEAK = 197e12
NB = 12800
R = 100

def run_total(make_body, *args):
    @jax.jit
    def run(*a):
        def body(i, acc):
            return acc + make_body(i, *a)
        return lax.fori_loop(0, R, body, jnp.float32(0))
    float(run(*args))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(*args))
        best = min(best, time.perf_counter() - t0)
    return best

BASE = run_total(lambda i, x: (x[0, 0, 0, 0] * (1 + i)).astype(jnp.float32),
                 jnp.ones((1, 1, 1, 1), jnp.bfloat16))
print(f"empty call total: {BASE*1e3:.1f} ms", flush=True)

def devtime(make_body, *args, tag="", flops=None, bytes_=None):
    total = run_total(make_body, *args)
    per = max(total - BASE, 1e-9) / R
    msg = f"{tag}: {per*1e3:.3f} ms/iter (total {total*1e3:.0f} ms)"
    if flops: msg += f"  ({flops/per/PEAK*100:.1f}% MFU)"
    if bytes_: msg += f"  ({bytes_/per/1e9:.0f} GB/s)"
    print(msg, flush=True)
    return per

K = 3
conv = lambda x, w: lax.conv_general_dilated(x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
x1 = jnp.asarray(rng.normal(size=(NB, 32, 32, 3)), jnp.bfloat16)
w1 = jnp.asarray(rng.normal(size=(K, K, 3, 32)), jnp.bfloat16)
f1 = NB * 32 * 32 * K * K * 3 * 32 * 2
devtime(lambda i, x: jax.nn.relu(x * (1 + 1e-6 * i)).mean().astype(jnp.float32), x1,
        tag="relu C=3        ", bytes_=2 * NB * 32 * 32 * 3 * 2)
devtime(lambda i, x, w: conv(x * (1 + 1e-6 * i), w).mean().astype(jnp.float32), x1, w1,
        tag="conv1 fwd       ", flops=f1)
x2 = jnp.asarray(rng.normal(size=(NB, 16, 16, 32)), jnp.bfloat16)
w2 = jnp.asarray(rng.normal(size=(K, K, 32, 64)), jnp.bfloat16)
f2 = NB * 16 * 16 * K * K * 32 * 64 * 2
devtime(lambda i, x, w: conv(x * (1 + 1e-6 * i), w).mean().astype(jnp.float32), x2, w2,
        tag="conv2 fwd       ", flops=f2)
N, M2, P2, C2 = 100, 32768, 288, 64
pa = jnp.asarray(rng.normal(size=(N, M2, P2)), jnp.bfloat16)
wb = jnp.asarray(rng.normal(size=(N, P2, C2)), jnp.bfloat16)
fb = 2 * N * M2 * P2 * C2
devtime(lambda i, a, b: lax.dot_general(a * (1 + 1e-6 * i), b, (((2,), (1,)), ((0,), (0,)))).mean().astype(jnp.float32),
        pa, wb, tag="batched GEMM    ", flops=fb)
pf = pa.reshape(N * M2, P2)
wfat = jnp.asarray(rng.normal(size=(P2, 128)), jnp.bfloat16)
devtime(lambda i, a, b: ((a * (1 + 1e-6 * i)) @ b).mean().astype(jnp.float32), pf, wfat,
        tag="GEMM K288 N128  ", flops=2 * N * M2 * P2 * 128)
A = jnp.asarray(rng.normal(size=(8192, 4096)), jnp.bfloat16)
Bm = jnp.asarray(rng.normal(size=(4096, 8192)), jnp.bfloat16)
devtime(lambda i, a, b: ((a * (1 + 1e-6 * i)) @ b).mean().astype(jnp.float32), A, Bm,
        tag="GEMM 8k/4k/8k   ", flops=2 * 8192 * 4096 * 8192)
