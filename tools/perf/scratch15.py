"""Scratch 15: where does the 32k TransformerLM train step lose 25x?
Device-side fori timing of: full step, attention-swap variants, and a
no-attention ablation."""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from tpfl.models import TransformerLM
from tpfl.parallel.flash_kernel import flash_attention

rng = np.random.default_rng(0)
S = 32768
toks = jnp.asarray(rng.integers(0, 256, (1, S)), jnp.int32)


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT: {BASE*1e3:.0f} ms", flush=True)


def measure(tag, attention_fn, R=5):
    lm = TransformerLM(
        vocab=256, dim=512, heads=8, n_layers=4, max_len=S,
        attention_fn=attention_fn,
    )
    variables = lm.init(jax.random.PRNGKey(0), toks[:, :128], train=False)
    tx = optax.sgd(1e-2, momentum=0.9)
    p0 = variables["params"]
    o0 = tx.init(p0)

    def one(p, o):
        def loss_of(pp):
            logits = lm.apply({"params": pp}, toks, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], toks[:, 1:]
            ).mean()

        loss, grads = jax.value_and_grad(loss_of)(p)
        up, o = tx.update(grads, o, p)
        return optax.apply_updates(p, up), o

    @jax.jit
    def run(p, o):
        return lax.fori_loop(0, R, lambda i, t: one(*t), (p, o))

    out = run(p0, o0)
    float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        out = run(p0, o0)
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    per = (best - BASE) / R
    print(f"{tag}: {per*1e3:.0f} ms/step  ({S/per:.0f} toks/s)", flush=True)
    return per


def no_attention(q, k, v, causal=True):
    return v  # ablation: attention replaced by identity on values


measure("no-attention ablation ", no_attention)
measure("flash block=1024      ", flash_attention)
