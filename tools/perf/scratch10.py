"""Scratch 10: TPU end-to-end vmapped train step with Pallas-backward
convs vs XLA baseline (22.03 ms), plus numeric sanity on-chip."""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from tpfl.models import CNN

rng = np.random.default_rng(0)
PEAK = 197e12
N, BS = 100, 128
R = 20


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT baseline: {BASE*1e3:.1f} ms", flush=True)

x_dev = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 3)), jnp.bfloat16)
y_dev = jnp.asarray(rng.integers(0, 10, (N, BS)), jnp.int32)
fs = (32 * 32 * 9 * 3 * 32 + 16 * 16 * 9 * 32 * 64 + 4096 * 128 + 128 * 10) * 2
f_step = 3 * fs * N * BS


def measure(tag, module):
    variables = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    p1 = variables["params"]
    params = jax.tree_util.tree_map(
        lambda q: jnp.broadcast_to(q[None], (N, *q.shape)) + 0, p1)
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.vmap(opt.init)(params)

    def one(pp, oo, xx, yy):
        def loss_of(q):
            logits = module.apply({"params": q}, xx, train=False)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

        loss, grads = jax.value_and_grad(loss_of)(pp)
        up, oo = opt.update(grads, oo, pp)
        return optax.apply_updates(pp, up), oo, loss

    def step(t, i):
        p, o, _ = t
        return jax.vmap(one)(p, o, x_dev, y_dev)

    @jax.jit
    def run(t):
        return lax.fori_loop(0, R, lambda i, t: step(t, i), t)

    t0 = (params, opt_state, jnp.zeros((N,), jnp.float32))
    out = run(t0)
    losses = np.asarray(out[2])
    best = float("inf")
    for _ in range(3):
        tt = time.perf_counter()
        out = run(t0)
        float(np.asarray(out[2]).mean())
        best = min(best, time.perf_counter() - tt)
    per = (best - BASE) / R
    print(f"{tag}: {per*1e3:.2f} ms  ({f_step/per/PEAK*100:.1f}% MFU)  "
          f"loss[:3]={np.asarray(out[2])[:3]}", flush=True)
    return out


out_p = measure("pallas-bwd step", CNN(out_channels=10, conv_impl="pallas"))
out_x = measure("xla-bwd step   ", CNN(out_channels=10, conv_impl="xla"))
# same trajectory? params after R steps should agree to bf16 tolerance
pa = jax.tree_util.tree_leaves(out_p[0])
px = jax.tree_util.tree_leaves(out_x[0])
errs = [float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max()) for a, b in zip(pa, px)]
print("max param divergence after 20 steps:", max(errs), flush=True)
