"""Re-open the per-node-conv formulation question with the r5 honest
floor. The r4 investigation measured the vmapped (grouped-conv
lowering) round at ~10.8% MFU and called it within noise of a 12.0%
shared-weight floor — but that floor was measured with the broken
sync (44 ms of device work vs ~90+/-15 ms subtracted RTT); the r5
floor is 16.3%, so there is a real 1.55x formulation gap.

Hypothesis worth one experiment: express the per-node conv as ONE
conv_general_dilated with ``batch_group_count=N`` (nodes ride the
batch dim, weights stack on the output-channel dim) instead of
vmap's feature_group_count lowering (groups of cin=3 input channels
— hopeless MXU tiles).

Times the full 2-conv train step (the scratch8 net) per formulation,
device fori_loop, scalar sync, RTT subtracted, best of 3.
"""

import os
import time

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..", ".jax_cache"
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

rng = np.random.default_rng(0)
PEAK = 197e12
N, BS = 100, 128
R = 20
DN = ("NHWC", "HWIO", "NHWC")


def _sync(out):
    float(np.asarray(jax.tree_util.tree_leaves(out)[-1]).ravel()[0])


def best_of(fn, *args, n=3):
    out = fn(*args)
    _sync(out)
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


@jax.jit
def empty_call(x):
    return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))


RTT, _ = best_of(empty_call, jnp.float32(1))
print(f"rtt={RTT * 1e3:.0f}ms", flush=True)


def conv_vmap(x, w):
    """x [N, BS, H, W, cin], w [N, 3, 3, cin, cout] — vmap lowering."""
    return jax.vmap(
        lambda xx, ww: lax.conv_general_dilated(
            xx, ww, (1, 1), "SAME", dimension_numbers=DN
        )
    )(x, w)


def conv_bgc(x, w):
    """Same math via ONE batch_group_count conv: [N*BS, H, W, cin] x
    [3, 3, cin, N*cout] with batch_group_count=N -> [BS', H, W, N*cout]
    ... batch groups convolve with their own output-channel block."""
    n, bs, h, ww_, cin = x.shape
    cout = w.shape[-1]
    xf = x.reshape(n * bs, h, ww_, cin)
    wf = jnp.moveaxis(w, 0, 3).reshape(3, 3, cin, n * cout)
    y = lax.conv_general_dilated(
        xf, wf, (1, 1), "SAME", dimension_numbers=DN, batch_group_count=n
    )
    # y: [BS, H, W, N*cout] with batch collapsed per group -> back to
    # [N, BS, H, W, cout]
    y = y.reshape(bs, h, ww_, n, cout)
    return jnp.moveaxis(y, 3, 0)


def make_step(conv):
    pool = lambda y: lax.reduce_window(
        y, -jnp.inf, lax.max, (1, 1, 2, 2, 1), (1, 1, 2, 2, 1), "VALID"
    )

    def net(params, x):
        y = conv(x, params["w1"])
        y = pool(jax.nn.relu(y + params["b1"][:, None, None, None, :]))
        y = conv(y, params["w2"])
        y = pool(jax.nn.relu(y + params["b2"][:, None, None, None, :]))
        y = y.reshape(y.shape[0], y.shape[1], -1)
        y = jax.nn.relu(jnp.einsum("nbf,nfd->nbd", y, params["wd"]) + params["bd"][:, None, :])
        return (
            jnp.einsum("nbd,ndo->nbo", y, params["wo"]) + params["bo"][:, None, :]
        ).astype(jnp.float32)

    opt = optax.sgd(0.1, momentum=0.9)

    def step(t):
        p, o = t

        def loss_of(q):
            logits = net(q, x_dev)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y_dev
            ).mean()

        loss, grads = jax.value_and_grad(loss_of)(p)
        up, o = opt.update(grads, o, p)
        return optax.apply_updates(p, up), o

    return step, opt


def init_params():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 6)
    p1 = {
        "w1": jax.random.normal(ks[0], (3, 3, 3, 32), jnp.bfloat16) * 0.1,
        "b1": jnp.zeros((32,), jnp.bfloat16),
        "w2": jax.random.normal(ks[1], (3, 3, 32, 64), jnp.bfloat16) * 0.05,
        "b2": jnp.zeros((64,), jnp.bfloat16),
        "wd": jax.random.normal(ks[2], (4096, 128), jnp.bfloat16) * 0.02,
        "bd": jnp.zeros((128,), jnp.bfloat16),
        "wo": jax.random.normal(ks[3], (128, 10), jnp.bfloat16) * 0.1,
        "bo": jnp.zeros((10,), jnp.bfloat16),
    }
    return jax.tree_util.tree_map(
        lambda q: jnp.broadcast_to(q[None], (N, *q.shape)) + 0, p1
    )


x_dev = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 3)), jnp.bfloat16)
y_dev = jnp.asarray(rng.integers(0, 10, (N, BS)), jnp.int32)

fs = (32 * 32 * 9 * 3 * 32 + 16 * 16 * 9 * 32 * 64 + 4096 * 128 + 128 * 10) * 2
f_step = 3 * fs * N * BS

# numeric check: both formulations agree
xt = jnp.asarray(rng.normal(size=(4, 2, 8, 8, 3)), jnp.float32)
wt = jnp.asarray(rng.normal(size=(4, 3, 3, 3, 5)), jnp.float32)
err = float(jnp.abs(conv_vmap(xt, wt) - conv_bgc(xt, wt)).max())
print("bgc-vs-vmap fwd err:", err, flush=True)
assert err < 1e-3


def measure(tag, conv):
    step, opt = make_step(conv)
    params = init_params()
    opt_state = jax.vmap(opt.init)(params)

    @jax.jit
    def run(t):
        out = lax.fori_loop(0, R, lambda i, tt: step(tt), t)
        leaves = jax.tree_util.tree_leaves(out)
        return sum(x.ravel()[0].astype(jnp.float32) for x in leaves)

    best, _ = best_of(run, (params, opt_state))
    per = (best - RTT) / R
    print(
        f"{tag}: {per * 1e3:.2f} ms  ({f_step / per / PEAK * 100:.1f}% MFU)",
        flush=True,
    )


measure("A vmap grouped conv ", conv_vmap)
measure("B batch_group_count ", conv_bgc)
