"""Scratch: op-level breakdown of the CNN fwd path + GEMM variants.

Sync discipline: block_until_ready is unreliable under the axon plugin —
every measurement syncs by pulling one element to host (D2H waits for
the producing program; device executes launches in order, so the final
sync drains the whole queue).
"""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
from jax import lax

rng = np.random.default_rng(0)
N, B, H, W, Cin, C1, C2, K = 100, 128, 32, 32, 3, 32, 64, 3
PEAK = 197e12
NB = N * B


def sync(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.ravel()[:1])


def timeit(fn, *args, n=10, tag="", flops=None):
    sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    dt = (time.perf_counter() - t0) / n
    msg = f"{tag}: {dt*1e3:.2f} ms"
    if flops:
        msg += f"  ({flops/dt/PEAK*100:.1f}% MFU)"
    print(msg, flush=True)
    return dt


x1 = jnp.asarray(rng.normal(size=(NB, H, W, Cin)), jnp.bfloat16)
w1 = jnp.asarray(rng.normal(size=(K, K, Cin, C1)), jnp.bfloat16)
x2 = jnp.asarray(rng.normal(size=(NB, H // 2, W // 2, C1)), jnp.bfloat16)
w2 = jnp.asarray(rng.normal(size=(K, K, C1, C2)), jnp.bfloat16)

conv = lambda x, w: lax.conv_general_dilated(
    x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))

f1 = NB * H * W * K * K * Cin * C1 * 2
f2 = NB * (H // 2) * (W // 2) * K * K * C1 * C2 * 2

timeit(jax.jit(conv), x1, w1, tag="conv1 fwd alone      ", flops=f1)
timeit(jax.jit(conv), x2, w2, tag="conv2 fwd alone      ", flops=f2)

pool = lambda y: lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
y1 = jnp.asarray(rng.normal(size=(NB, H, W, C1)), jnp.bfloat16)
timeit(jax.jit(lambda y: pool(jax.nn.relu(y))), y1, tag="relu+pool on conv1out")

# whole shared-weight 2-conv fwd, for a consistent baseline with D2H sync
def net_shared(x, wa, wb):
    y = conv(x, wa)
    y = jax.nn.relu(y)
    y = pool(y)
    return conv(y, wb)

timeit(jax.jit(net_shared), x1, w1, w2, tag="shared net fwd       ", flops=f1 + f2)
g_sh = jax.jit(jax.grad(lambda wa, wb: jnp.sum(net_shared(x1, wa, wb).astype(jnp.float32) ** 2), argnums=(0, 1)))
timeit(g_sh, w1, w2, tag="shared net fwd+bwd   ", flops=3 * (f1 + f2))

# GEMM variants for conv2 shape
M2, P2 = B * (H // 2) * (W // 2), K * K * C1
pa = jnp.asarray(rng.normal(size=(N, M2, P2)), jnp.bfloat16)
wb = jnp.asarray(rng.normal(size=(N, P2, C2)), jnp.bfloat16)
fb = 2 * N * M2 * P2 * C2

timeit(jax.jit(lambda a, b: lax.dot_general(a, b, (((2,), (1,)), ((0,), (0,))))),
       pa, wb, tag="batched GEMM n-major ", flops=fb)

pa_flat = pa.reshape(N * M2, P2)
wb1 = wb[0]
timeit(jax.jit(lambda a, b: a @ b), pa_flat, wb1, tag="single GEMM shared   ", flops=fb)

try:
    gs = jnp.full((N,), M2, jnp.int32)
    timeit(jax.jit(lambda a, b, g: lax.ragged_dot(a, b, g)), pa_flat, wb, gs,
           tag="ragged_dot           ", flops=fb)
except Exception as e:
    print("ragged_dot failed:", str(e)[:200], flush=True)

wb128 = jnp.concatenate([wb1, wb1], 1)
timeit(jax.jit(lambda a, b: a @ b), pa_flat, wb128, tag="single GEMM N=128    ", flops=2 * fb)
