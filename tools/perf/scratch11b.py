"""Scratch 11: standalone Pallas kernel timings (vmapped over nodes) +
single-step grad parity vs XLA on TPU."""
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np
from jax import lax

from tpfl.parallel.conv_kernel import _DN, node_conv

rng = np.random.default_rng(0)
PEAK = 197e12
N, BS = 100, 128
R = 20


def rtt():
    @jax.jit
    def run(x):
        return lax.fori_loop(0, 100, lambda i, a: a + x * (1 + i), jnp.float32(0))

    float(run(jnp.float32(1)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


BASE = rtt()
print(f"RTT baseline: {BASE*1e3:.1f} ms", flush=True)


def devloop(fn, tree0, tag, flops=None):
    @jax.jit
    def run(t):
        return lax.fori_loop(0, R, lambda i, t: fn(t, i), t)

    out = run(tree0)
    float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = run(tree0)
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    per = (best - BASE) / R
    msg = f"{tag}: {per*1e3:.2f} ms"
    if flops:
        msg += f"  ({flops/per/PEAK*100:.1f}% MFU)"
    print(msg, flush=True)


def vgrad(conv):
    def per_node(x, w, d):
        _, vjp = jax.vjp(lambda ww: conv(x, ww), w)
        return vjp(d)[0]

    return jax.vmap(per_node)


conv_k = lambda x, w: node_conv(x, w, False)
conv_x = lambda x, w: lax.conv_general_dilated(
    x, w, (1, 1), "SAME", dimension_numbers=_DN)

# conv2 shapes
x2 = jnp.asarray(rng.normal(size=(N, BS, 16, 16, 32)), jnp.bfloat16)
w2 = jnp.asarray(rng.normal(size=(N, 3, 3, 32, 64)), jnp.bfloat16) * 0.1
d2 = jnp.asarray(rng.normal(size=(N, BS, 16, 16, 64)), jnp.bfloat16)
f2 = 2 * N * BS * 256 * 288 * 64

gk = jax.jit(vgrad(conv_k))
gx = jax.jit(vgrad(conv_x))

# full vjp x-grad parity
def vgrad_x(conv):
    def per_node(x, w, d):
        _, vjp = jax.vjp(lambda xx: conv(xx, w), x)
        return vjp(d)[0]

    return jax.vmap(per_node)


def time1(tag, fn, x, w, d, flops):
    def step(t, i):
        out = fn(x, w * (1 + 1e-6 * i), d)
        return (t[0] + out.astype(jnp.float32).ravel()[0],)

    devloop(step, (jnp.float32(0),), tag, flops)


time1("pallas conv2 dW", gk, x2, w2, d2, f2)
time1("pallas conv2 dx", vgrad_x(conv_k), x2, w2, d2, f2)

# conv1 shapes
x1 = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 3)), jnp.bfloat16)
w1 = jnp.asarray(rng.normal(size=(N, 3, 3, 3, 32)), jnp.bfloat16) * 0.1
d1 = jnp.asarray(rng.normal(size=(N, BS, 32, 32, 32)), jnp.bfloat16)
f1 = 2 * N * BS * 1024 * 27 * 32
time1("pallas conv1 dW", gk, x1, w1, d1, f1)
