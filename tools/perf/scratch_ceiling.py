"""Scratch: component ceilings for the 100-node CNN round on one v5e chip."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

rng = np.random.default_rng(0)
N, B, H, W, Cin, C1, C2, K = 100, 128, 32, 32, 3, 32, 64, 3
PEAK = 197e12


def timeit(fn, *args, n=10, tag="", flops=None):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    msg = f"{tag}: {dt*1e3:.2f} ms"
    if flops:
        msg += f"  ({flops/dt/PEAK*100:.1f}% MFU)"
    print(msg)
    return dt


# (a) shared-weight net, nodes folded into batch — the ceiling
x_big = jnp.asarray(rng.normal(size=(N * B, H, W, Cin)), jnp.bfloat16)
w1s = jnp.asarray(rng.normal(size=(K, K, Cin, C1)), jnp.bfloat16)
w2s = jnp.asarray(rng.normal(size=(K, K, C1, C2)), jnp.bfloat16)


def net_shared(x, wa, wb):
    y = lax.conv_general_dilated(x, wa, (1, 1), "SAME",
                                 dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y = jax.nn.relu(y)
    y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    y = lax.conv_general_dilated(y, wb, (1, 1), "SAME",
                                 dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y


f_fwd = N * B * (H * W * K * K * Cin * C1 + (H // 2) * (W // 2) * K * K * C1 * C2) * 2

g_shared = jax.jit(jax.grad(lambda wa, wb: jnp.sum(net_shared(x_big, wa, wb).astype(jnp.float32) ** 2), argnums=(0, 1)))
timeit(g_shared, w1s, w2s, tag="shared-weight fwd+bwd", flops=3 * f_fwd)

fwd_shared = jax.jit(lambda wa, wb: net_shared(x_big, wa, wb))
timeit(fwd_shared, w1s, w2s, tag="shared-weight fwd    ", flops=f_fwd)

# (b) batched GEMM alone, conv2 shape: [N, M2, P2] @ [N, P2, C2]
M2, P2 = B * (H // 2) * (W // 2), K * K * C1
pa = jnp.asarray(rng.normal(size=(N, M2, P2)), jnp.bfloat16)
wb2 = jnp.asarray(rng.normal(size=(N, P2, C2)), jnp.bfloat16)
bg = jax.jit(lambda a, b: lax.dot_general(a, b, (((2,), (1,)), ((0,), (0,)))))
timeit(bg, pa, wb2, tag="batched GEMM conv2   ", flops=2 * N * M2 * P2 * C2)

# conv1-shaped batched GEMM: [N, B*H*W, 27] @ [N, 27, 32]
M1, P1 = B * H * W, K * K * Cin
pa1 = jnp.asarray(rng.normal(size=(N, M1, P1)), jnp.bfloat16)
wb1 = jnp.asarray(rng.normal(size=(N, P1, C1)), jnp.bfloat16)
timeit(bg, pa1, wb1, tag="batched GEMM conv1   ", flops=2 * N * M1 * P1 * C1)

# (c) patch extraction alone (both convs), node-folded
ex1 = jax.jit(lambda x: lax.conv_general_dilated_patches(
    x, (K, K), (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")))
timeit(ex1, x_big, tag="patches conv1        ")
y_mid = jnp.asarray(rng.normal(size=(N * B, H // 2, W // 2, C1)), jnp.bfloat16)
timeit(ex1, y_mid, tag="patches conv2        ")

# (d) grouped-conv lowering of the vmapped conv2 (what XLA does today)
xs2 = jnp.asarray(rng.normal(size=(N, B, H // 2, W // 2, C1)), jnp.bfloat16)
w2b = jnp.asarray(rng.normal(size=(N, K, K, C1, C2)), jnp.bfloat16)
vc = jax.jit(jax.vmap(lambda x, w: lax.conv_general_dilated(
    x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))))
timeit(vc, xs2, w2b, tag="vmapped conv2 (XLA)  ", flops=2 * N * M2 * P2 * C2)
